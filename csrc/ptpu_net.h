// ptpu_net — the shared event-driven network core under BOTH native
// servers (csrc/ptpu_ps_server.cc data plane, csrc/ptpu_serving.cc
// inference runtime). Reference counterpart: the brpc event-dispatcher
// + Socket layer every distributed service in the upstream project
// rides (PAPER.md §1 services rows) — rebuilt here as one epoll core
// so C10K-scale connection counts stop costing one std::thread each.
//
// Shape:
//   * 1 blocking acceptor thread + N event threads, each owning a
//     private epoll set; accepted connections are assigned round-robin
//     and then touched ONLY by their owner loop (no cross-thread
//     socket reads, no per-connection locks on the read path).
//   * Per-connection state machine speaking the existing u32-LE frame
//     protocol (ptpu_wire.h) and the HMAC-SHA256 nonce handshake
//     (ptpu_hmac.h): nonblocking partial reads accumulate into a
//     per-conn buffer; complete frames dispatch to the server's
//     frame handler; replies queue on the conn and flush with one
//     writev per wakeup (several replies coalesce into one syscall).
//   * Foreign-thread replies (the serving micro-batcher finishing a
//     batch on an instance worker) enqueue under the conn's out-lock
//     and wake the owner loop over an eventfd — workers never block
//     on a slow client's socket.
//   * Deadlines: a handshake that does not complete within
//     handshake_timeout_us is cut (slow-loris shedding); idle
//     connections close after idle_timeout_us (0 = never). A
//     max-conns cap sheds at accept time. Stop() drains gracefully:
//     stop accepting -> flush queued replies -> close.
//
// Threading contract (TSan-verified by csrc/ptpu_net_selftest.cc):
// everything per-connection except {outq_, pool_, closed_,
// flush_posted_} is owner-loop-only; those four are guarded by omu_.
// The frame handler runs on the owner loop; Conn::SendPayload /
// SendCopy / AcquireBuf / Close are safe from any thread.
#ifndef PTPU_NET_H_
#define PTPU_NET_H_

#include <atomic>
#include <cstdint>
#include <deque>
#include <functional>
#include <memory>
#include <string>
#include <thread>
#include <vector>

#include "ptpu_stats.h"
#include "ptpu_sync.h"

namespace ptpu {
namespace net {

// Lock classes of the net core (rank table: README "Correctness
// tooling"). Event loops take at most ONE of these at a time; the
// conn out-lock is the LAST lock on any reply path (a batcher worker
// may reach it holding serving-side locks, never the reverse).
PTPU_LOCK_CLASS(kLockConnOut, "net.conn_out", 100);
PTPU_LOCK_CLASS(kLockInbox, "net.inbox", 110);

// Net-core counters, embedded in each server's stats block and
// rendered into its stats_json (twin names documented in
// tools/ptpu_check.py PS_SERVER_C_ONLY).
struct Stats {
  // conns_closed counts every close of a COUNTED (framed, non-HTTP)
  // conn, whatever the reason — the paired term that makes
  //   conns_accepted == active_conns + conns_closed
  // a conservation law (ptpu_invar manifest, csrc/ptpu_invar.h)
  // instead of folklore.
  Counter conns_accepted, conns_closed, conns_shed, handshake_fails,
      handshake_timeouts, idle_closes, epoll_wakeups,
      partial_write_flushes, http_reqs;
  // Injected-fault counters (PTPU_CHAOS drills): every fault the net
  // core injects is COUNTED here so a chaos soak can reconcile what
  // the server says happened against what clients observed — exact
  // equality is the pass condition, not "roughly the right number".
  Counter chaos_conn_kills, chaos_read_delays, chaos_write_delays,
      chaos_short_writes, chaos_handshake_drops;
  std::atomic<int64_t> active_conns{0};

  void Reset() {
    // Invariant-preserving by construction (ISSUE 20): zeroing the
    // flow counters while connections are open would leave
    // conns_accepted (0) != active_conns (k) + conns_closed (0), and
    // no multi-counter read is atomic against racing accept/close.
    // Instead REBASE both sides of the conn_balance law by the same
    // amount (closed-so-far): accepted - b == active + (closed - b)
    // holds whenever accepted == active + closed did, for ANY racing
    // interleaving. Post-reset semantics: conns_accepted counts
    // still-open conns plus accepts since the reset.
    const uint64_t closed_base = conns_closed.Get();
    conns_accepted.Rebase(closed_base);
    conns_closed.Rebase(closed_base);
    conns_shed.Reset();
    // close-reason subsets may zero outright: every future reason
    // bump pairs a conns_closed bump, so `closed >= reasons` keeps
    // holding over the post-reset window
    handshake_fails.Reset();
    handshake_timeouts.Reset();
    idle_closes.Reset();
    epoll_wakeups.Reset();
    partial_write_flushes.Reset();
    http_reqs.Reset();
    chaos_conn_kills.Reset();
    chaos_read_delays.Reset();
    chaos_write_delays.Reset();
    chaos_short_writes.Reset();
    chaos_handshake_drops.Reset();
    // active_conns is a live gauge, not a counter: reset must not
    // forget currently-open connections
  }
};

// Env-gated fault injection (the chaos half of the ptpu_drill
// harness): PTPU_CHAOS="kinds:rate" turns faults on for BOTH servers,
// where kinds is a comma list of {kill,rdelay,wdelay,shortw,hsdrop}
// (or "all") and rate N injects on 1-in-N eligible events. Unset (the
// default) and malformed values leave every fault OFF — production
// pays one branch per site. PTPU_CHAOS_DELAY_US sizes the rdelay /
// wdelay stalls. Each injected fault increments its Stats counter,
// and every kind maps onto a failure the core already survives:
//   kill   — close an OPEN conn just before its next frame dispatch
//            (peer sees EOF mid-pipeline, like a server crash)
//   rdelay — stall before draining a readable socket (rx scheduling
//            jitter / packet delay)
//   wdelay — stall before a writev flush (tx congestion)
//   shortw — cap one flush to a single byte, forcing the partial-
//            write EPOLLOUT path (tiny socket buffers); lossless
//   hsdrop — reject a VALID handshake MAC (flaky auth / mid-deploy
//            key skew); client sees the normal handshake-fail close
struct ChaosConfig {
  bool kill = false;
  bool rdelay = false;
  bool wdelay = false;
  bool shortw = false;
  bool hsdrop = false;
  int64_t rate = 0;          // 0 = off; N = 1-in-N eligible events
  int64_t delay_us = 2000;   // rdelay/wdelay stall length
  bool enabled() const {
    return rate > 0 && (kill || rdelay || wdelay || shortw || hsdrop);
  }
};

struct Options {
  int port = 0;                 // 0 = pick a free one
  bool loopback_only = true;
  std::string authkey;
  int event_threads = 0;        // <= 0: min(8, max(2, hw/2))
  int64_t max_conns = 0;        // <= 0: 65536; above it, accept+close
  int64_t handshake_timeout_us = 5 * 1000 * 1000;
  int64_t idle_timeout_us = 0;  // 0 = never idle-close
  int64_t defer_retry_us = 500; // kDefer re-dispatch cadence
  int64_t drain_timeout_us = 5 * 1000 * 1000;
  uint32_t max_frame = 1u << 30;
  int listen_backlog = 512;
  int sockbuf_bytes = 4 << 20;  // SO_SNDBUF/SO_RCVBUF (<=0: kernel)
  // Per-connection cap on queued unsent reply bytes: a client that
  // stops READING must not grow server memory without bound (the
  // epoll-core replacement for the old SO_SNDTIMEO conn-break) —
  // past the cap the connection is closed.
  size_t max_out_bytes = 64u << 20;
  // Second protocol: a minimal HTTP/1.1 GET responder (telemetry:
  // /metrics, /healthz, /statsz, /tracez) served by the SAME event
  // threads from a second listen socket (the acceptor thread polls
  // both — no new threads). -1 disables; 0 picks a free port. The
  // HTTP listener keeps accepting through StopAccepting() (health
  // probes must reach a draining server) and closes at Drain().
  int http_port = -1;
  // Fault injection for production drills (see ChaosConfig above).
  // Default-constructed = fully off; OptionsFromEnv fills it from
  // PTPU_CHAOS / PTPU_CHAOS_DELAY_US.
  ChaosConfig chaos;
};

// Apply the PTPU_NET_* env knobs on top of `base` (both servers call
// this so one tuning story covers them): PTPU_NET_THREADS,
// PTPU_NET_MAX_CONNS, PTPU_NET_HANDSHAKE_US, PTPU_NET_IDLE_US,
// PTPU_NET_SOCKBUF, PTPU_NET_MAX_OUT (the per-connection queued-reply
// byte cap that cuts slow readers), PTPU_NET_HTTP (telemetry HTTP
// port: -1 off, 0 free pick), and the chaos drill knobs PTPU_CHAOS
// ("kinds:rate") + PTPU_CHAOS_DELAY_US. Unset/invalid vars keep the
// base value.
Options OptionsFromEnv(Options base);

// Frame-handler verdict for one dispatched frame.
enum class FrameResult {
  kOk,     // frame consumed; keep parsing
  kClose,  // close the connection (protocol violation / hangup)
  kDefer,  // keep THIS frame unconsumed and re-dispatch it after
           // defer_retry_us; reads from this conn pause meanwhile
           // (bounded backpressure without blocking the event thread)
};

class EventLoop;
class Server;

// One external segment of a scatter reply (SendScatter): `n` wire
// bytes read straight from `p` — typically a predictor arena output
// block — without ever being copied into a reply buffer.
struct OutSeg {
  const uint8_t* p = nullptr;
  size_t n = 0;
};

class Conn : public std::enable_shared_from_this<Conn> {
 public:
  // Queue one frame for sending: buf = [4 reserved bytes][payload];
  // the u32-LE length prefix is written here. Thread-safe. Returns
  // false once the connection is closed (the buffer is dropped).
  // `trace_id` nonzero records a net.flush span (queue time -> last
  // byte written) with `trace_arg` into the shared ptpu_trace ring
  // when the buffer fully drains.
  bool SendPayload(std::vector<uint8_t>&& buf, uint64_t trace_id = 0,
                   uint64_t trace_arg = 0);
  // Convenience copy form for small frames (errors, acks, meta).
  bool SendCopy(const uint8_t* payload, size_t n);
  // Scatter send (zero-copy replies): the frame's wire bytes are
  // head[4..] followed by every segment in order, written with the
  // same coalescing writev as SendPayload — the segments are never
  // copied. `head` = [4 reserved bytes][header fields]; the u32-LE
  // length prefix (covering head payload + all segments) is written
  // here. `pin` keeps the memory behind every segment alive until
  // the net core has flushed the frame's last byte (or the conn
  // dies: close/backpressure-kill drop the queue and release it).
  // Thread-safe.
  bool SendScatter(std::vector<uint8_t>&& head,
                   std::vector<OutSeg>&& segs, std::shared_ptr<void> pin,
                   uint64_t trace_id = 0, uint64_t trace_arg = 0);
  // Verbatim bytes, NO u32 length prefix (HTTP responses). Same
  // queue/flush/backpressure path as SendPayload. Thread-safe.
  bool SendRaw(std::vector<uint8_t>&& buf);
  // Pooled reply buffer (size 0, capacity reused across frames on
  // this conn — steady-state replies never reallocate). Thread-safe.
  std::vector<uint8_t> AcquireBuf();
  // Request an asynchronous close from any thread.
  void Close();
  // Microseconds the currently-dispatched frame has been deferred
  // (0 on first dispatch) — handlers budget their kDefer retries
  // against this. Owner-loop only (valid inside the frame handler).
  int64_t deferred_us() const;

  // Zero-copy ingestion: pin the reassembly buffer backing the
  // currently-dispatched frame so `payload` stays valid after the
  // handler returns (kDefer stashes, micro-batcher gathers straight
  // from the wire bytes). While any pin is live the buffer is
  // append-only — the event loop swaps in a fresh buffer instead of
  // compacting/growing in place, so pinned pointers never move.
  // Returns nullptr when `payload` does not live in this conn's
  // buffer (a Detached fuzz conn pumping foreign memory): callers
  // must copy then. Owner-loop only (valid inside the frame handler).
  std::shared_ptr<const void> PinInbuf(const uint8_t* payload,
                                       size_t n);

  // Stable per-connection id (monotonic across the process), stamped
  // at accept — the `conn` field of every trace span. Thread-safe.
  uint64_t id() const { return id_; }

  // When the currently-dispatched frame's first bytes were read off
  // the socket (steady-clock us) — the net.read span's begin stamp.
  // Owner-loop only (valid inside the frame handler); 0 if unknown.
  int64_t frame_recv_us() const { return frame_t0_; }

  // Count of requests this connection has in flight OUTSIDE the net
  // core (e.g. queued in the serving micro-batcher): while nonzero
  // the idle timeout treats the conn as active even though no bytes
  // are moving. Thread-safe; the server pairs +1 on handoff with -1
  // when the reply (or its error) is queued.
  void NotePending(int64_t delta) {
    pending_work_.fetch_add(delta, std::memory_order_relaxed);
  }

  // Per-connection server state (owned by the server's callbacks:
  // allocate in on_open, free in on_close).
  void* user = nullptr;

  // Fuzz/test hook: a connection owned by NO event loop (fd -1, state
  // open). Send*/AcquireBuf queue replies without flushing, so a
  // harness can pump frame payloads straight into a server's on_frame
  // handler with zero sockets in the loop (csrc/fuzz/*). Queued
  // replies die with the object; past max_out_bytes the conn closes
  // like a live one.
  static std::shared_ptr<Conn> Detached(size_t max_out_bytes = 64u << 20);

 private:
  friend class EventLoop;
  friend class Server;

  struct OutBuf {
    std::vector<uint8_t> b;       // owned head bytes (whole frame when
                                  // segs is empty)
    std::vector<OutSeg> segs;     // external scatter segments after b
    size_t seg_bytes = 0;         // sum of segs[i].n
    std::shared_ptr<void> pin;    // keeps segment memory alive
    size_t off = 0;               // flushed offset into b ++ segs
    uint64_t trace_id = 0, trace_arg = 0;  // net.flush span (if traced)
    int64_t t_queued = 0;
    size_t total() const { return b.size() + seg_bytes; }
  };

  // shared enqueue/backpressure/flush-post body of all send forms
  bool EnqueueOut(OutBuf&& ob, uint64_t trace_id, uint64_t trace_arg);

  // ---- accept-time constants (never change after adoption) ----
  uint64_t id_ = 0;     // process-wide monotonic connection id
  bool http_ = false;   // second protocol: HTTP/1.1 GET telemetry

  // ---- owner-loop state (never touched by other threads) ----
  int fd_ = -1;
  EventLoop* loop_ = nullptr;
  enum class St { kAwaitMac, kOpen, kClosed };
  St state_ = St::kAwaitMac;
  uint8_t nonce_[16] = {0};
  // Reassembly buffer, shared so PinInbuf can extend its lifetime
  // past the frame handler's return. use_count() > 1 means pinned:
  // ReserveIn/MaybeResetIn then swap in a fresh buffer rather than
  // moving bytes (appends at in_tail_ never move existing data).
  std::shared_ptr<std::vector<uint8_t>> in_ =
      std::make_shared<std::vector<uint8_t>>();
  size_t in_head_ = 0, in_tail_ = 0;
  // Ensure >= need writable bytes after in_tail_ (compact or grow;
  // pin-aware). MaybeResetIn rewinds head/tail to 0 when the buffer
  // is fully parsed AND unpinned. Owner-loop only.
  void ReserveIn(size_t need);
  void MaybeResetIn() {
    if (in_head_ == in_tail_ && in_.use_count() == 1)
      in_head_ = in_tail_ = 0;
  }
  int64_t frame_t0_ = 0;  // first bytes of the pending frame read at
  bool want_write_ = false;     // EPOLLOUT armed
  bool read_paused_ = false;    // EPOLLIN disarmed (kDefer)
  bool http_close_ = false;     // close once the response flushes
  int64_t handshake_deadline_ = 0;
  int64_t idle_deadline_ = 0;   // 0 = none
  int64_t defer_since_ = 0;     // 0 = not deferring
  int64_t defer_retry_at_ = 0;
  std::atomic<int64_t> pending_work_{0};  // see NotePending

  // ---- shared state (guarded by omu_) ----
  Mutex omu_{kLockConnOut};
  std::deque<OutBuf> outq_;
  std::vector<std::vector<uint8_t>> pool_;
  size_t out_bytes_ = 0;         // queued unsent bytes
  size_t max_out_bytes_ = 0;     // set at accept from Options
  bool closed_ = false;
  bool flush_posted_ = false;
};

using ConnPtr = std::shared_ptr<Conn>;

// ---- HTTP request-head parsing (pure functions, fuzzed directly by
// csrc/fuzz/fuzz_http.cc; the buffered state machine around them is
// split-point-tested in csrc/ptpu_net_selftest.cc) ----

// Offset one past the CRLFCRLF header terminator within [data, len),
// or 0 when the buffer does not yet hold a complete head.
size_t HttpHeaderEnd(const char* data, size_t len);

// One parsed HTTP/1.x request head (GET-only telemetry).
struct HttpReqHead {
  bool ok = false;          // request line had METHOD SP target SP ...
  std::string method;
  std::string target;       // path + query string, verbatim
  bool keep_alive = true;   // 1.1 default; Connection header honored
};

// Parse the request line + keep-alive semantics of one complete head
// (`head_len` as returned by HttpHeaderEnd).
HttpReqHead ParseHttpRequestHead(const char* data, size_t head_len);

// One telemetry HTTP response (GET only; built inline on the event
// thread, so handlers must not block).
struct HttpReply {
  int status = 200;
  std::string content_type = "text/plain; charset=utf-8";
  std::string body;
};

// The shared telemetry routes both servers mount on their second
// (HTTP) listener: /healthz (503 {"status":"draining"} when
// `draining`), /statsz (stats_json()), /metrics (the C Prometheus
// renderer over the same snapshot, family prefix `prom_prefix`),
// /tracez?n=K (the shared ptpu_trace ring), and /capturez?n=K (the
// shared ptpu_capture frame ring). Anything else is 404.
HttpReply TelemetryHttp(const std::string& target,
                        const std::function<std::string()>& stats_json,
                        const std::string& prom_prefix, bool draining);

struct Callbacks {
  // Handshake completed; runs on the owner loop. Optional.
  std::function<void(const ConnPtr&)> on_open;
  // Connection fully closed (fires exactly once); owner loop. Free
  // conn->user here. Optional.
  std::function<void(const ConnPtr&)> on_close;
  // One complete frame (payload WITHOUT the 4-byte length prefix).
  // Runs on the owner loop; must not block.
  std::function<FrameResult(const ConnPtr&, const uint8_t*, uint32_t)>
      on_frame;
  // A frame length above max_frame arrived (the conn is closed right
  // after) — servers count their proto_errors here. Optional.
  std::function<void(const ConnPtr&)> on_oversize;
  // One HTTP GET on the telemetry listener (path includes the query
  // string). Runs on the owner loop; must not block. Required when
  // Options::http_port >= 0.
  std::function<HttpReply(const std::string& path)> on_http;
};

class Server {
 public:
  Server(const Options& opt, Callbacks cbs, Stats* stats);
  ~Server();  // Stop()

  // Bind + listen + start the acceptor and event threads. Returns
  // false with *err set on failure (nothing keeps running).
  bool Start(std::string* err);
  int port() const { return port_; }
  // Telemetry HTTP port (-1 when disabled).
  int http_port() const { return http_port_; }

  // Graceful stop, in two callable halves so servers can quiesce
  // their own pipelines in between (serving: stop accepting, drain
  // the micro-batcher so in-flight requests still answer, THEN flush
  // + close): StopAccepting() stops the FRAMED listener (the HTTP
  // telemetry listener keeps answering health probes during the
  // quiesce window); Drain() closes both listeners, flushes every
  // conn's queued replies (bounded by drain_timeout_us), closes, and
  // joins the event threads.
  void StopAccepting();
  void Drain();
  void Stop();  // StopAccepting(); Drain();

 private:
  friend class EventLoop;

  void AcceptLoop();
  // Accept + configure one connection off `lfd`; returns false when
  // the listener is dead (shutdown or fatal errno).
  bool AcceptOne(int lfd, bool http);

  Options opt_;
  Callbacks cbs_;
  Stats* stats_;
  int listen_fd_ = -1;
  int http_fd_ = -1;
  int port_ = 0;
  int http_port_ = -1;
  std::atomic<bool> stop_accept_{false};
  std::atomic<bool> stop_http_{false};
  std::atomic<bool> drained_{false};
  std::thread accept_thread_;
  std::vector<std::unique_ptr<EventLoop>> loops_;
  size_t next_loop_ = 0;
};

}  // namespace net
}  // namespace ptpu

#endif  // PTPU_NET_H_
