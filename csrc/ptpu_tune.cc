// extern "C" ABI surface over the persisted kernel-autotuning
// registry (ptpu_tune.h). Process-global per .so, same model as the
// trace ring: the registry itself is header-only so the single-TU
// selftests and fuzz harnesses share one definition with the
// predictor; only these exports need a dedicated TU in the
// _native_predictor.so link.
#include "ptpu_tune.h"

extern "C" {

/* Autotuner counters as JSON: {"enabled","entries","hits","misses",
 * "probes","probe_us","file_loads","file_entries","file_rejects",
 * "wrong_cpu","saves","save_errors"}. Thread-local buffer, valid
 * until the calling thread's next call. */
__attribute__((visibility("default")))
const char* ptpu_tune_stats_json(void) {
  thread_local std::string buf;
  buf = ptpu::tune::Registry::Inst().StatsJson();
  return buf.c_str();
}

/* Persist the current winners to `path` (NULL/empty = the
 * PTPU_TUNE_CACHE default). Returns entries written, -1 on I/O
 * error. Forces a write even when nothing is dirty so bindings can
 * snapshot. */
__attribute__((visibility("default")))
int ptpu_tune_save(const char* path) {
  const std::string p = (path != nullptr && path[0] != '\0')
                            ? std::string(path)
                            : ptpu::tune::Registry::DefaultPath();
  return ptpu::tune::Registry::Inst().SaveIfDirty(p);
}

/* Merge-load a tuning cache from `path` (NULL/empty = the default).
 * Returns entries adopted; corrupt or wrong-machine files adopt 0
 * and never error — the contract is silent re-probe. */
__attribute__((visibility("default")))
int ptpu_tune_load(const char* path) {
  const std::string p =
      (path != nullptr && path[0] != '\0') ? std::string(path) : std::string();
  return ptpu::tune::Registry::Inst().LoadFile(p);
}

/* Drop every in-memory entry and counter (the cache FILE is left
 * untouched). Tests use this to force re-probe in one process. */
__attribute__((visibility("default")))
void ptpu_tune_clear(void) { ptpu::tune::Registry::Inst().Clear(); }

}  // extern "C"
