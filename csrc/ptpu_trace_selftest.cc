// Unit tests for the shared span recorder + Prometheus renderer
// (single-TU include of ptpu_trace.cc — cc_test analogue, run by
// `make selftest` and both sancheck legs; no Python, no sockets).
#include <cassert>
#include <cstdio>
#include <cstring>
#include <set>
#include <string>
#include <thread>
#include <vector>

#include "ptpu_trace.cc"

using ptpu::trace::Config;
using ptpu::trace::Recorder;
using ptpu::trace::SpanRec;
using ptpu::trace::SpanView;
using ptpu::trace::SlowView;

static int g_tests = 0;

#define CHECK(cond)                                                    \
  do {                                                                 \
    if (!(cond)) {                                                     \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__,     \
                   #cond);                                             \
      return 1;                                                        \
    }                                                                  \
  } while (0)

#define TEST(name)                                                     \
  std::fprintf(stderr, "trace_selftest: %s\n", name);                  \
  ++g_tests;

int main() {
  {
    TEST("ring wraparound exactness");
    Config cfg;
    cfg.sample = 1;
    cfg.slow_us = 0;
    cfg.ring = 64;
    Recorder r(cfg);
    // write 1000 spans with trace_id == i+1; the ring keeps exactly
    // the newest 64, in order, with every field intact
    for (uint64_t i = 0; i < 1000; ++i)
      r.Record(i + 1, ptpu::trace::kRun, int64_t(10 * i),
               int64_t(10 * i + 5), /*conn=*/7, /*arg=*/i);
    CHECK(r.recorded() == 1000);
    std::vector<SpanView> got;
    r.Snapshot(&got, 1000);
    CHECK(got.size() == 64);
    for (size_t k = 0; k < got.size(); ++k) {
      const uint64_t want = 1000 - k;  // newest first
      CHECK(got[k].trace_id == want);
      CHECK(got[k].kind == ptpu::trace::kRun);
      CHECK(got[k].t0_us == int64_t(10 * (want - 1)));
      CHECK(got[k].t1_us == int64_t(10 * (want - 1) + 5));
      CHECK(got[k].conn == 7);
      CHECK(got[k].arg == want - 1);
    }
    // max_n clamps
    r.Snapshot(&got, 3);
    CHECK(got.size() == 3 && got[0].trace_id == 1000);
  }

  {
    TEST("sampled-off zero-cost path");
    Config cfg;
    cfg.sample = 0;
    cfg.slow_us = 0;
    Recorder r(cfg);
    for (int i = 0; i < 10000; ++i) {
      CHECK(r.BeginRequest(0) == 0);
      // a client-sent trace id is ALSO off while the kill switch is
      // set: PTPU_TRACE_SAMPLE=0 must mean zero recorder work
      CHECK(r.BeginRequest(0xdeadbeefull) == 0);
    }
    r.Record(0, ptpu::trace::kRead, 1, 2, 3, 4);  // tid 0: no-op
    CHECK(r.recorded() == 0);
    CHECK(!r.SlowEligible(INT64_MAX / 2));
    std::vector<SpanView> got;
    r.Snapshot(&got, 16);
    CHECK(got.empty());
  }

  {
    TEST("sampling: 1-in-N dice + client ids always win");
    Config cfg;
    cfg.sample = 4;
    Recorder r(cfg);
    int hits = 0;
    for (int i = 0; i < 400; ++i)
      if (r.BeginRequest(0)) ++hits;
    CHECK(hits == 100);  // deterministic counter dice, exactly 1-in-4
    // a client id is returned verbatim, no dice roll
    for (int i = 0; i < 10; ++i)
      CHECK(r.BeginRequest(42) == 42);
    // generated ids are unique and nonzero
    std::set<uint64_t> ids;
    Config all = cfg;
    all.sample = 1;
    Recorder r2(all);
    for (int i = 0; i < 1000; ++i) {
      const uint64_t id = r2.BeginRequest(0);
      CHECK(id != 0);
      ids.insert(id);
    }
    CHECK(ids.size() == 1000);
  }

  {
    TEST("runtime Set() override");
    Config cfg;
    cfg.sample = 0;
    Recorder r(cfg);
    CHECK(r.BeginRequest(7) == 0);
    r.Set(1, 250);
    CHECK(r.sample() == 1 && r.slow_us() == 250);
    CHECK(r.BeginRequest(7) == 7);
    CHECK(r.SlowEligible(250) && !r.SlowEligible(249));
    r.Set(-1, -1);  // negative keeps current
    CHECK(r.sample() == 1 && r.slow_us() == 250);
  }

  {
    TEST("slow ring: bounded capture with full breakdown");
    Config cfg;
    cfg.sample = 1;
    cfg.slow_us = 100;
    cfg.slow_ring = 8;
    Recorder r(cfg);
    for (int i = 0; i < 20; ++i) {
      SpanRec sp[3] = {{ptpu::trace::kRead, 10 * i, 10 * i + 1},
                       {ptpu::trace::kQueue, 10 * i + 1, 10 * i + 4},
                       {ptpu::trace::kRun, 10 * i + 4, 10 * i + 9}};
      r.RecordSlow(uint64_t(i + 1), /*conn=*/3, /*req=*/uint64_t(i),
                   /*e2e=*/1000 + i, sp, 3);
    }
    std::vector<SlowView> got;
    r.SnapshotSlow(&got);
    CHECK(got.size() == 8);
    for (size_t k = 0; k < got.size(); ++k) {
      const uint64_t want = 20 - k;  // newest first
      CHECK(got[k].trace_id == want);
      CHECK(got[k].e2e_us == int64_t(1000 + want - 1));
      CHECK(got[k].spans.size() == 3);
      CHECK(got[k].spans[0].kind == ptpu::trace::kRead);
      CHECK(got[k].spans[2].kind == ptpu::trace::kRun);
      CHECK(got[k].spans[2].t1_us - got[k].spans[2].t0_us == 5);
    }
    // span count clamps at kSlowSpans
    SpanRec many[12] = {};
    for (int i = 0; i < 12; ++i)
      many[i] = {ptpu::trace::kRead, i, i + 1};
    r.RecordSlow(99, 0, 0, 500, many, 12);
    r.SnapshotSlow(&got);
    CHECK(got[0].trace_id == 99);
    CHECK(int(got[0].spans.size()) == Recorder::kSlowSpans);
  }

  {
    TEST("threaded recorder consistency (4 writers x 25k)");
    Config cfg;
    cfg.sample = 1;
    cfg.ring = 1024;
    Recorder r(cfg);
    constexpr int kThreads = 4, kPer = 25000;
    std::vector<std::thread> ts;
    for (int t = 0; t < kThreads; ++t)
      ts.emplace_back([&r, t] {
        for (int i = 0; i < kPer; ++i)
          r.Record(uint64_t(t) * kPer + i + 1,
                   uint8_t(i % ptpu::trace::kKindCount), i, i + 1,
                   uint64_t(t), uint64_t(i));
      });
    // concurrent readers must never see torn records
    std::thread reader([&r] {
      std::vector<SpanView> got;
      for (int i = 0; i < 200; ++i) {
        r.Snapshot(&got, 256);
        for (const auto& v : got) {
          assert(v.trace_id != 0);
          assert(v.t1_us == v.t0_us + 1);
          assert(v.kind < ptpu::trace::kKindCount);
        }
      }
    });
    for (auto& t : ts) t.join();
    reader.join();
    CHECK(r.recorded() == uint64_t(kThreads) * kPer);
    std::vector<SpanView> got;
    r.Snapshot(&got, 4096);
    // Quiescent: no slot is mid-write, but a writer that claimed
    // index X and stalled past a later writer on the same slot
    // (X + ring) leaves that slot's seq at the OLDER generation, and
    // Snapshot rightly skips it — at most one slot per concurrent
    // stale writer, so kThreads-1 worst case.
    CHECK(got.size() >= 1024 - (kThreads - 1));
    CHECK(got.size() <= 1024);
    for (const auto& v : got) CHECK(v.t1_us == v.t0_us + 1);
  }

  {
    TEST("tracez JSON shape");
    Config cfg;
    cfg.sample = 2;
    cfg.slow_us = 50;
    cfg.ring = 64;
    Recorder r(cfg);
    r.Record(5, ptpu::trace::kPull, 100, 200, 9, 512);
    SpanRec sp[1] = {{ptpu::trace::kPull, 100, 200}};
    r.RecordSlow(5, 9, 512, 100, sp, 1);
    const std::string j = r.TracezJson(16);
    CHECK(j.find("\"sample\":2") != std::string::npos);
    CHECK(j.find("\"slow_us\":50") != std::string::npos);
    CHECK(j.find("\"ring\":64") != std::string::npos);
    CHECK(j.find("\"recorded\":1") != std::string::npos);
    CHECK(j.find("\"spans\":[{\"kind\":\"ps.pull\",\"t0_us\":100,"
                 "\"t1_us\":200,\"trace_id\":5,\"conn\":9,\"arg\":512}"
                 "]") != std::string::npos);
    CHECK(j.find("\"slow\":[{\"trace_id\":5,\"conn\":9,\"req\":512,"
                 "\"e2e_us\":100,\"spans\":[{\"kind\":\"ps.pull\","
                 "\"t0_us\":100,\"t1_us\":200}]}]") !=
          std::string::npos);
  }

  {
    TEST("span-kind name table is dense and distinct");
    std::set<std::string> names;
    for (int k = 0; k < ptpu::trace::kKindCount; ++k) {
      CHECK(ptpu::trace::kSpanKindNames[k] != nullptr);
      CHECK(std::strlen(ptpu::trace::kSpanKindNames[k]) > 0);
      names.insert(ptpu::trace::kSpanKindNames[k]);
    }
    CHECK(int(names.size()) == ptpu::trace::kKindCount);
  }

  {
    TEST("Prometheus renderer: counters, labels, cumulative buckets");
    // a miniature stats snapshot in exactly the renderers' grammar
    const std::string snap =
        "{\"server\":{\"pull_ops\":3,\"lat_us\":{\"count\":4,"
        "\"sum\":30,\"buckets\":[1,2,0,1]}},"
        "\"tables\":{\"emb\":{\"wire\":{\"rows\":7}},"
        "\"w2\":{\"wire\":{\"rows\":9}}}}";
    const std::string got =
        ptpu::trace::PromFromStatsJson(snap, "ptpu_ps");
    const std::string want =
        "# TYPE ptpu_ps_server_pull_ops counter\n"
        "ptpu_ps_server_pull_ops 3\n"
        "# TYPE ptpu_ps_server_lat_us histogram\n"
        "ptpu_ps_server_lat_us_bucket{le=\"0\"} 1\n"
        "ptpu_ps_server_lat_us_bucket{le=\"1\"} 3\n"
        "ptpu_ps_server_lat_us_bucket{le=\"3\"} 3\n"
        "ptpu_ps_server_lat_us_bucket{le=\"+Inf\"} 4\n"
        "ptpu_ps_server_lat_us_sum 30\n"
        "ptpu_ps_server_lat_us_count 4\n"
        "# TYPE ptpu_ps_table_wire_rows counter\n"
        "ptpu_ps_table_wire_rows{table=\"emb\"} 7\n"
        "ptpu_ps_table_wire_rows{table=\"w2\"} 9\n";
    if (got != want) {
      std::fprintf(stderr, "prom mismatch:\n--- got ---\n%s--- want "
                           "---\n%s",
                   got.c_str(), want.c_str());
      return 1;
    }
    // malformed input never crashes
    CHECK(ptpu::trace::PromFromStatsJson("{broken", "x").find(
              "did not parse") != std::string::npos);
    CHECK(ptpu::trace::PromFromStatsJson("", "x").find(
              "did not parse") != std::string::npos);
  }

  std::fprintf(stderr, "ptpu_trace_selftest: %d tests OK\n", g_tests);
  return 0;
}
