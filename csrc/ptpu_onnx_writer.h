// Tiny hand-rolled ONNX/protobuf WRITER — test/fuzz infrastructure
// only, never linked into a shipping .so. One copy shared by the
// serving selftest (csrc/ptpu_serving_selftest.cc round-trip
// artifacts) and the fuzz harnesses (csrc/fuzz/: structure-aware
// seed artifacts for the ONNX-loader and serving-wire targets). The
// field numbers mirror exactly the subset csrc/ptpu_predictor.cc's
// parse_model consumes (ModelProto.graph = 7; GraphProto node = 1,
// initializer = 5, input = 11, output = 12; NodeProto input = 1,
// output = 2, op_type = 4, attribute = 5; TensorProto dims = 1,
// data_type = 2, name = 8, raw_data = 9).
#ifndef PTPU_ONNX_WRITER_H_
#define PTPU_ONNX_WRITER_H_

#include <cstdint>
#include <string>
#include <vector>

namespace ptpu {
namespace onnxw {

inline void put_varint(std::string* s, uint64_t v) {
  while (v >= 0x80) {
    s->push_back(char(v | 0x80));
    v >>= 7;
  }
  s->push_back(char(v));
}

inline void put_tag(std::string* s, int field, int wire) {
  put_varint(s, uint64_t(field) << 3 | unsigned(wire));
}

inline void put_u64f(std::string* s, int field, uint64_t v) {
  put_tag(s, field, 0);
  put_varint(s, v);
}

inline void put_lenf(std::string* s, int field,
                     const std::string& payload) {
  put_tag(s, field, 2);
  put_varint(s, payload.size());
  s->append(payload);
}

inline std::string onnx_tensor_f32(const std::string& name,
                                   const std::vector<int64_t>& dims,
                                   const float* data, size_t n) {
  std::string t;
  for (int64_t d : dims) put_u64f(&t, 1, uint64_t(d));
  put_u64f(&t, 2, 1);  // data_type f32
  put_lenf(&t, 8, name);
  put_lenf(&t, 9,
           std::string(reinterpret_cast<const char*>(data), n * 4));
  return t;
}

inline std::string onnx_tensor_i64(const std::string& name,
                                   const std::vector<int64_t>& dims,
                                   const std::vector<int64_t>& data) {
  std::string t;
  for (int64_t d : dims) put_u64f(&t, 1, uint64_t(d));
  put_u64f(&t, 2, 7);  // data_type i64
  put_lenf(&t, 8, name);
  put_lenf(&t, 9,
           std::string(reinterpret_cast<const char*>(data.data()),
                       data.size() * 8));
  return t;
}

inline std::string onnx_value_info(const std::string& name, int elem,
                                   const std::vector<int64_t>& dims) {
  std::string shape;
  for (int64_t d : dims) {
    std::string dim;
    put_u64f(&dim, 1, uint64_t(d));
    put_lenf(&shape, 1, dim);
  }
  std::string tt;
  put_u64f(&tt, 1, uint64_t(elem));
  put_lenf(&tt, 2, shape);
  std::string ty;
  put_lenf(&ty, 1, tt);
  std::string vi;
  put_lenf(&vi, 1, name);
  put_lenf(&vi, 2, ty);
  return vi;
}

inline std::string onnx_node(const std::string& op,
                             const std::vector<std::string>& ins,
                             const std::vector<std::string>& outs) {
  std::string n;
  for (const auto& i : ins) put_lenf(&n, 1, i);
  for (const auto& o : outs) put_lenf(&n, 2, o);
  put_lenf(&n, 4, op);
  return n;
}

// node with one integer attribute (Cast's `to`)
inline std::string onnx_node_iattr(const std::string& op,
                                   const std::vector<std::string>& ins,
                                   const std::vector<std::string>& outs,
                                   const std::string& aname,
                                   int64_t aval) {
  std::string n = onnx_node(op, ins, outs);
  std::string a;
  put_lenf(&a, 1, aname);
  put_u64f(&a, 3, uint64_t(aval));
  put_lenf(&n, 5, a);
  return n;
}

}  // namespace onnxw
}  // namespace ptpu

#endif  // PTPU_ONNX_WRITER_H_
