// SHA-256 + HMAC-SHA256 (public-domain-style compact implementation) —
// the connect-handshake MAC shared by the PS data-plane server
// (csrc/ptpu_ps_server.cc) and the inference serving runtime
// (csrc/ptpu_serving.cc). Header-only so each .so stays
// dependency-free; restates the multiprocessing.connection HMAC
// challenge for C peers that cannot speak Python's banner format.
#ifndef PTPU_HMAC_H_
#define PTPU_HMAC_H_

#include <algorithm>
#include <cstdint>
#include <cstring>

namespace ptpu {

struct Sha256 {
  uint32_t h[8];
  uint64_t len = 0;
  uint8_t buf[64];
  size_t buf_n = 0;

  Sha256() {
    static const uint32_t init[8] = {
        0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
        0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
    std::memcpy(h, init, sizeof(h));
  }

  static uint32_t Rotr(uint32_t x, int n) {
    return (x >> n) | (x << (32 - n));
  }

  void Block(const uint8_t *p) {
    static const uint32_t k[64] = {
        0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b,
        0x59f111f1, 0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01,
        0x243185be, 0x550c7dc3, 0x72be5d74, 0x80deb1fe, 0x9bdc06a7,
        0xc19bf174, 0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
        0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da, 0x983e5152,
        0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
        0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc,
        0x53380d13, 0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
        0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3, 0xd192e819,
        0xd6990624, 0xf40e3585, 0x106aa070, 0x19a4c116, 0x1e376c08,
        0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f,
        0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
        0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};
    uint32_t w[64];
    for (int i = 0; i < 16; ++i)
      w[i] = uint32_t(p[4 * i]) << 24 | uint32_t(p[4 * i + 1]) << 16 |
             uint32_t(p[4 * i + 2]) << 8 | p[4 * i + 3];
    for (int i = 16; i < 64; ++i) {
      const uint32_t s0 =
          Rotr(w[i - 15], 7) ^ Rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
      const uint32_t s1 =
          Rotr(w[i - 2], 17) ^ Rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5],
             g = h[6], hh = h[7];
    for (int i = 0; i < 64; ++i) {
      const uint32_t s1 = Rotr(e, 6) ^ Rotr(e, 11) ^ Rotr(e, 25);
      const uint32_t ch = (e & f) ^ (~e & g);
      const uint32_t t1 = hh + s1 + ch + k[i] + w[i];
      const uint32_t s0 = Rotr(a, 2) ^ Rotr(a, 13) ^ Rotr(a, 22);
      const uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
      const uint32_t t2 = s0 + maj;
      hh = g;
      g = f;
      f = e;
      e = d + t1;
      d = c;
      c = b;
      b = a;
      a = t1 + t2;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
  }

  void Update(const uint8_t *p, size_t n) {
    len += n;
    while (n) {
      const size_t take = std::min(n, sizeof(buf) - buf_n);
      std::memcpy(buf + buf_n, p, take);
      buf_n += take;
      p += take;
      n -= take;
      if (buf_n == 64) {
        Block(buf);
        buf_n = 0;
      }
    }
  }

  void Final(uint8_t out[32]) {
    const uint64_t bits = len * 8;
    const uint8_t one = 0x80, zero = 0;
    Update(&one, 1);
    while (buf_n != 56) Update(&zero, 1);
    uint8_t lenb[8];
    for (int i = 0; i < 8; ++i) lenb[i] = uint8_t(bits >> (56 - 8 * i));
    Update(lenb, 8);
    for (int i = 0; i < 8; ++i) {
      out[4 * i] = uint8_t(h[i] >> 24);
      out[4 * i + 1] = uint8_t(h[i] >> 16);
      out[4 * i + 2] = uint8_t(h[i] >> 8);
      out[4 * i + 3] = uint8_t(h[i]);
    }
  }
};

inline void HmacSha256(const uint8_t *key, size_t key_n,
                       const uint8_t *msg, size_t msg_n,
                       uint8_t out[32]) {
  uint8_t k[64] = {0};
  if (key_n > 64) {
    Sha256 s;
    s.Update(key, key_n);
    s.Final(k);
  } else {
    std::memcpy(k, key, key_n);
  }
  uint8_t ipad[64], opad[64];
  for (int i = 0; i < 64; ++i) {
    ipad[i] = k[i] ^ 0x36;
    opad[i] = k[i] ^ 0x5c;
  }
  uint8_t inner[32];
  Sha256 si;
  si.Update(ipad, 64);
  si.Update(msg, msg_n);
  si.Final(inner);
  Sha256 so;
  so.Update(opad, 64);
  so.Update(inner, 32);
  so.Final(out);
}

}  // namespace ptpu

#endif  // PTPU_HMAC_H_
