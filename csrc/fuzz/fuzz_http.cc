// Fuzz target: the HTTP telemetry request parser — header-end scan
// (HttpHeaderEnd), request-line + keep-alive parsing
// (ParseHttpRequestHead), the /tracez?n= whole-key query parser, and
// the shared route dispatch (TelemetryHttp, which renders /statsz,
// /metrics via the JSON walker, and /tracez). These are the bytes any
// local process can throw at the telemetry port pre-auth.
//
// The buffered reassembly state machine AROUND these functions
// (partial reads, 431 header cap, keep-alive loop) is split-point
// driven by csrc/ptpu_net_selftest.cc and end-to-end by
// csrc/fuzz/fuzz_frames.cc.
//
// Corpus: csrc/fuzz/corpus/http (every route incl. query forms, bad
// request lines, 1.0/1.1 keep-alive shapes). Build: `make fuzz`.
#include "../ptpu_net.cc"
#include "../ptpu_trace.cc"

#include <cstdint>
#include <string>

namespace {

std::string FakeStatsJson() {
  // the shape both servers emit: nested objects, counters, one hist
  return "{\"server\":{\"pull_ops\":3,\"pull_us\":{\"count\":2,"
         "\"sum\":10,\"buckets\":[1,1]}},\"tables\":{\"t\":{\"wire\":"
         "{\"bytes_in\":7}}}}";
}

}  // namespace

extern "C" int LLVMFuzzerTestOneInput(const uint8_t* data, size_t size) {
  if (size > (256u << 10)) return 0;
  const char* p = reinterpret_cast<const char*>(data);
  const size_t end = ptpu::net::HttpHeaderEnd(p, size);
  const size_t head_len = end ? end : size;  // also parse partials
  const ptpu::net::HttpReqHead head =
      ptpu::net::ParseHttpRequestHead(p, head_len);
  if (head.ok) {
    // route dispatch exactly as both servers mount it (the target
    // string is attacker-shaped: path + query, verbatim)
    (void)ptpu::net::TelemetryHttp(head.target, FakeStatsJson,
                                   "ptpu_fuzz", false);
    (void)ptpu::net::TelemetryHttp(head.target, FakeStatsJson, "",
                                   true);
  }
  return 0;
}
