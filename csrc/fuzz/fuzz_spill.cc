// Fuzz target: the KV spill-tier parsers — ptpu::spill's
// ParseSpillHeader / ParseHibBytes / ParsePrefixBytes in
// csrc/ptpu_spill.h (ISSUE 19). All three read UNTRUSTED DISK INPUT:
// the spill-file header is re-read on every attach, hibernation
// records round-trip through callers that may persist them, and the
// prefix-persist file warms the adopt index across restarts — so the
// parsers get the same r11 treatment as wire frames and the tune
// cache: bounds-checked, fuzzed, whole-file reject on any malformed
// byte, never a crash.
//
// Harness shape: the same bytes feed all three parsers (their magics
// disambiguate). Well-formed inputs additionally round-trip through
// the matching Serialize* and must re-parse identically —
// canonicalization bugs abort here, not as a silently rewritten file
// in production. The prefix parser needs a geometry to validate
// against; it is derived from the input's own header words (capped),
// so mutations can both match and mismatch the pinned geometry.
//
// Corpus: csrc/fuzz/corpus/spill (valid files of each flavour,
// truncations, huge counts, bit flips, wrong versions —
// csrc/fuzz/gen_seeds.py). Build: `make fuzz`.
#include "../ptpu_spill.h"

#include <cassert>
#include <cstdint>
#include <cstring>
#include <vector>

extern "C" int LLVMFuzzerTestOneInput(const uint8_t* data, size_t size) {
  if (size > (1u << 20)) return 0;
  namespace sp = ptpu::spill;
  // 1) spill-file header: parse + canonical round trip
  {
    sp::SpillGeom g;
    if (sp::ParseSpillHeader(data, size, &g) == sp::ParseResult::kOk) {
      assert(sp::GeomValid(g));
      uint8_t buf[sp::kSpillHeaderBytes];
      sp::SerializeSpillHeader(g, buf);
      sp::SpillGeom again;
      assert(sp::ParseSpillHeader(buf, sizeof(buf), &again) ==
             sp::ParseResult::kOk);
      assert(again.page == g.page && again.layers == g.layers &&
             again.heads == g.heads && again.hdim == g.hdim &&
             again.slot_bytes == g.slot_bytes);
    }
  }
  // 2) hibernation record: parse + canonical round trip
  {
    sp::HibRecord rec;
    if (sp::ParseHibBytes(data, size, &rec) == sp::ParseResult::kOk) {
      std::vector<uint8_t> bytes;
      sp::SerializeHib(rec, &bytes);
      assert(bytes.size() == size);
      sp::HibRecord again;
      assert(sp::ParseHibBytes(bytes.data(), bytes.size(), &again) ==
             sp::ParseResult::kOk);
      assert(again.hib_id == rec.hib_id && again.len == rec.len &&
             again.groups.size() == rec.groups.size());
      for (size_t i = 0; i < rec.groups.size(); ++i) {
        assert(again.groups[i].kind == rec.groups[i].kind &&
               again.groups[i].a == rec.groups[i].a &&
               again.groups[i].b == rec.groups[i].b);
      }
    }
  }
  // 3) prefix-persist file: the caller pins the pool geometry, so
  // derive it from the input's own header words — valid seeds parse
  // kOk against their embedded geometry while any mutation of those
  // words exercises the geometry-mismatch rejects too. Caps keep a
  // hostile header from allocating GeomElems-sized scratch.
  if (size >= sp::kPrefixHeaderBytes) {
    const auto clamp = [](uint32_t v, uint32_t cap) {
      return (v >= 1 && v <= cap) ? v : (v % cap) + 1;
    };
    sp::SpillGeom g;
    g.page = clamp(ptpu::GetU32(data + 8), 8);
    g.layers = clamp(ptpu::GetU32(data + 12), 4);
    g.heads = clamp(ptpu::GetU32(data + 16), 4);
    g.hdim = clamp(ptpu::GetU32(data + 20), 8);
    g.slot_bytes = uint64_t(g.layers) * 2 * g.page * g.heads * g.hdim *
                   sizeof(float);
    std::vector<sp::PrefixRec> recs;
    if (sp::ParsePrefixBytes(data, size, g, &recs) ==
        sp::ParseResult::kOk) {
      std::vector<uint8_t> bytes;
      sp::SerializePrefix(recs, g, &bytes);
      assert(bytes.size() == size);
      assert(std::memcmp(bytes.data(), data, size) == 0);
      std::vector<sp::PrefixRec> again;
      assert(sp::ParsePrefixBytes(bytes.data(), bytes.size(), g,
                                  &again) == sp::ParseResult::kOk);
      assert(again.size() == recs.size());
    }
  }
  return 0;
}
