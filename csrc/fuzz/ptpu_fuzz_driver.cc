// ptpu_fuzz driver — the in-tree coverage-guided fuzzing engine under
// every harness in csrc/fuzz/ (ISSUE 11).
//
// Why not libFuzzer: the baked toolchain is GCC-only (no clang, no
// compiler-rt fuzzer archive), but GCC has shipped the SAME
// instrumentation hook libFuzzer rides since GCC 6:
// -fsanitize-coverage=trace-pc calls __sanitizer_cov_trace_pc() at
// every edge. This TU supplies that callback (it is compiled WITHOUT
// the coverage flag — instrumenting the engine itself recurses into
// a stack overflow, measured) plus a minimal AFL-shaped mutation
// loop over it. Harnesses keep the standard libFuzzer contract —
//   extern "C" int LLVMFuzzerTestOneInput(const uint8_t*, size_t);
//   extern "C" int LLVMFuzzerInitialize(int*, char***);  // optional
// — so if a clang toolchain ever appears, the same harness sources
// link against real libFuzzer unchanged.
//
// Modes (tools/run_checks.sh uses both):
//   <target> DIR|FILE...              replay every input once (the CI
//                                     corpus-regression leg; exit 0 ==
//                                     every input survived)
//   <target> -fuzz=SECS [-runs=N] DIR coverage-guided mutation loop
//                                     seeded from DIR; -out=DIR writes
//                                     inputs that reach new edges back
//                                     to a corpus dir (default: none —
//                                     CI smoke must not mutate the
//                                     checked-in corpus)
//   -max_len=N (default 1 MiB), -seed=N, -timeout=SECS (per-input
//   alarm, default 20), -artifact=PREFIX (crash dump location,
//   default ./crash-)
//
// Crash handling: the current input lives in a global; ASan's death
// callback (and a SIGSEGV/SIGABRT/SIGALRM fallback) dumps it to
// <artifact><len>-<hash> before the process dies, so every finding is
// reproducible with `<target> <crash-file>`. Findings get MINIMIZED
// by hand-replay and committed to csrc/fuzz/corpus/ as regressions.
#include <dirent.h>
#include <fcntl.h>
#include <signal.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

extern "C" int LLVMFuzzerTestOneInput(const uint8_t* data, size_t size);
extern "C" int __attribute__((weak))
LLVMFuzzerInitialize(int* argc, char*** argv);

// ---------------------------------------------------------------------------
// Coverage map (AFL-style edge hash over return addresses). The
// callback must stay minimal and allocation-free: it runs at every
// instrumented edge of the target TU.
// ---------------------------------------------------------------------------

namespace {

constexpr size_t kMapBits = 16;
constexpr size_t kMapSize = 1u << kMapBits;
uint8_t g_cov[kMapSize];
size_t g_cov_count = 0;
thread_local uintptr_t g_prev_pc = 0;

}  // namespace

extern "C" void __sanitizer_cov_trace_pc() {
  const uintptr_t pc =
      reinterpret_cast<uintptr_t>(__builtin_return_address(0));
  const size_t idx = (pc ^ (g_prev_pc >> 1)) & (kMapSize - 1);
  g_prev_pc = pc;
  if (!g_cov[idx]) {
    g_cov[idx] = 1;
    ++g_cov_count;
  }
}

// ASan runtime hook: called once when the process is about to die on
// a sanitizer report. Weak so the uninstrumented build still links.
extern "C" void __attribute__((weak))
__sanitizer_set_death_callback(void (*cb)());

namespace {

// ---------------------------------------------------------------------------
// Crash artifact dump (async-signal-safe: open/write only)
// ---------------------------------------------------------------------------

const uint8_t* g_cur_data = nullptr;
size_t g_cur_size = 0;
char g_artifact_prefix[512] = "./crash-";

uint64_t Fnv1a(const uint8_t* d, size_t n) {
  uint64_t h = 1469598103934665603ull;
  for (size_t i = 0; i < n; ++i) h = (h ^ d[i]) * 1099511628211ull;
  return h;
}

void DumpCurrentInput() {
  if (!g_cur_data) return;
  char path[640];
  const uint64_t h = Fnv1a(g_cur_data, g_cur_size);
  std::snprintf(path, sizeof(path), "%s%zu-%016llx", g_artifact_prefix,
                g_cur_size, (unsigned long long)h);
  const int fd = ::open(path, O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return;
  ssize_t w = ::write(fd, g_cur_data, g_cur_size);
  (void)w;
  ::close(fd);
  // stderr is fd 2; keep it async-signal-safe
  const char* msg = "\nptpu_fuzz: crashing input written to ";
  w = ::write(2, msg, std::strlen(msg));
  w = ::write(2, path, std::strlen(path));
  w = ::write(2, "\n", 1);
  (void)w;
}

void CrashSignal(int sig) {
  DumpCurrentInput();
  ::signal(sig, SIG_DFL);
  ::raise(sig);
}

// ---------------------------------------------------------------------------
// Corpus
// ---------------------------------------------------------------------------

struct Input {
  std::vector<uint8_t> bytes;
  std::string path;  // empty for in-memory mutants
};

bool ReadFileBytes(const std::string& path, std::vector<uint8_t>* out) {
  FILE* f = std::fopen(path.c_str(), "rb");
  if (!f) return false;
  std::fseek(f, 0, SEEK_END);
  const long n = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  out->resize(n > 0 ? size_t(n) : 0);
  const size_t got = n > 0 ? std::fread(out->data(), 1, size_t(n), f) : 0;
  std::fclose(f);
  out->resize(got);
  return true;
}

void LoadCorpus(const std::string& arg, std::vector<Input>* corpus) {
  struct stat st;
  if (::stat(arg.c_str(), &st) != 0) {
    std::fprintf(stderr, "ptpu_fuzz: cannot stat %s\n", arg.c_str());
    std::exit(2);
  }
  if (S_ISDIR(st.st_mode)) {
    DIR* d = ::opendir(arg.c_str());
    if (!d) return;
    std::vector<std::string> names;
    while (dirent* e = ::readdir(d)) {
      if (e->d_name[0] == '.') continue;
      names.push_back(arg + "/" + e->d_name);
    }
    ::closedir(d);
    std::sort(names.begin(), names.end());  // deterministic replay
    for (const auto& p : names) {
      Input in;
      in.path = p;
      if (ReadFileBytes(p, &in.bytes)) corpus->push_back(std::move(in));
    }
  } else {
    Input in;
    in.path = arg;
    if (ReadFileBytes(arg, &in.bytes)) corpus->push_back(std::move(in));
  }
}

// ---------------------------------------------------------------------------
// Mutator (AFL havoc subset; xorshift RNG for determinism under -seed)
// ---------------------------------------------------------------------------

uint64_t g_rng = 88172645463325252ull;

uint64_t Rnd() {
  g_rng ^= g_rng << 13;
  g_rng ^= g_rng >> 7;
  g_rng ^= g_rng << 17;
  return g_rng;
}

size_t RndBelow(size_t n) { return n ? size_t(Rnd() % n) : 0; }

const int64_t kInteresting[] = {0,    1,    -1,   16,   32,   64,
                                100,  127,  -128, 255,  256,  512,
                                1024, 4096, 65535, 65536, 1 << 20,
                                -(1 << 20)};

void Mutate(std::vector<uint8_t>* b, size_t max_len,
            const std::vector<Input>& corpus) {
  const int rounds = 1 + int(RndBelow(8));
  for (int r = 0; r < rounds; ++r) {
    if (b->empty()) {
      b->push_back(uint8_t(Rnd()));
      continue;
    }
    switch (RndBelow(10)) {
      case 0:  // bit flip
        (*b)[RndBelow(b->size())] ^= uint8_t(1u << RndBelow(8));
        break;
      case 1:  // random byte
        (*b)[RndBelow(b->size())] = uint8_t(Rnd());
        break;
      case 2: {  // interesting value, random width/endian-free
        const int64_t v =
            kInteresting[RndBelow(sizeof(kInteresting) /
                                  sizeof(kInteresting[0]))];
        const size_t w = size_t(1) << RndBelow(4);  // 1/2/4/8
        const size_t pos = RndBelow(b->size());
        for (size_t i = 0; i < w && pos + i < b->size(); ++i)
          (*b)[pos + i] = uint8_t(uint64_t(v) >> (8 * i));
        break;
      }
      case 3: {  // delete a block
        const size_t pos = RndBelow(b->size());
        const size_t n = 1 + RndBelow(std::min<size_t>(
                                 b->size() - pos, 1 + b->size() / 4));
        b->erase(b->begin() + pos, b->begin() + pos + n);
        break;
      }
      case 4: {  // duplicate / insert a block
        if (b->size() >= max_len) break;
        const size_t pos = RndBelow(b->size());
        const size_t n = 1 + RndBelow(std::min<size_t>(
                                 b->size() - pos,
                                 std::min<size_t>(max_len - b->size(),
                                                  256)));
        std::vector<uint8_t> blk(b->begin() + pos,
                                 b->begin() + pos + n);
        b->insert(b->begin() + RndBelow(b->size()), blk.begin(),
                  blk.end());
        break;
      }
      case 5: {  // insert random bytes
        if (b->size() >= max_len) break;
        const size_t n = 1 + RndBelow(16);
        std::vector<uint8_t> blk(n);
        for (auto& c : blk) c = uint8_t(Rnd());
        b->insert(b->begin() + RndBelow(b->size() + 1), blk.begin(),
                  blk.end());
        break;
      }
      case 6: {  // splice with another corpus input
        if (corpus.empty()) break;
        const auto& other = corpus[RndBelow(corpus.size())].bytes;
        if (other.empty()) break;
        const size_t cut_a = RndBelow(b->size());
        const size_t cut_b = RndBelow(other.size());
        b->resize(cut_a);
        b->insert(b->end(), other.begin() + cut_b, other.end());
        if (b->size() > max_len) b->resize(max_len);
        break;
      }
      case 7: {  // overwrite with a chunk from another input
        if (corpus.empty()) break;
        const auto& other = corpus[RndBelow(corpus.size())].bytes;
        if (other.empty()) break;
        const size_t pos = RndBelow(b->size());
        const size_t n =
            std::min(b->size() - pos, 1 + RndBelow(other.size()));
        const size_t src = RndBelow(other.size() - n + 1);
        std::memcpy(b->data() + pos, other.data() + src, n);
        break;
      }
      case 8: {  // arithmetic +-1..16 on a byte
        uint8_t& c = (*b)[RndBelow(b->size())];
        c = uint8_t(c + int(RndBelow(33)) - 16);
        break;
      }
      default: {  // truncate
        b->resize(1 + RndBelow(b->size()));
        break;
      }
    }
  }
  if (b->size() > max_len) b->resize(max_len);
}

// ---------------------------------------------------------------------------
// Execution
// ---------------------------------------------------------------------------

int64_t NowMs() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return int64_t(ts.tv_sec) * 1000 + ts.tv_nsec / 1000000;
}

unsigned g_timeout_s = 20;

// Runs one input; returns true when it reached new coverage.
bool RunOne(const uint8_t* data, size_t size) {
  g_cur_data = data;
  g_cur_size = size;
  const size_t before = g_cov_count;
  g_prev_pc = 0;
  if (g_timeout_s) ::alarm(g_timeout_s);
  LLVMFuzzerTestOneInput(data, size);
  if (g_timeout_s) ::alarm(0);
  g_cur_data = nullptr;
  return g_cov_count > before;
}

void WriteCorpusFile(const std::string& dir,
                     const std::vector<uint8_t>& b) {
  char name[600];
  std::snprintf(name, sizeof(name), "%s/auto-%016llx", dir.c_str(),
                (unsigned long long)Fnv1a(b.data(), b.size()));
  FILE* f = std::fopen(name, "wb");
  if (!f) return;
  std::fwrite(b.data(), 1, b.size(), f);
  std::fclose(f);
}

}  // namespace

// Sanitizer knobs, baked so every invocation (CI, sustained runs,
// replay) behaves identically: huge hostile allocations must FAIL
// (bad_alloc reaches the parser's error path) instead of aborting the
// fuzzer, and leaks are findings.
// default visibility: the whole tree builds -fvisibility=hidden, and a
// hidden default-options hook is invisible to the sanitizer runtime
// (observed: UBSan exiting without a stack or artifact dump)
extern "C" __attribute__((visibility("default"))) const char*
__asan_default_options() {
  return "allocator_may_return_null=1:malloc_context_size=12:"
         "detect_leaks=1:abort_on_error=1";
}
extern "C" __attribute__((visibility("default"))) const char*
__ubsan_default_options() {
  // abort (not _exit) so the SIGABRT hook dumps the crashing
  // input even when the report comes from standalone UBSan
  return "print_stacktrace=1:abort_on_error=1:halt_on_error=1";
}

int main(int argc, char** argv) {
  int64_t fuzz_secs = 0, max_runs = 0;
  size_t max_len = 1u << 20;
  std::vector<std::string> corpus_args;
  std::string out_dir;
  uint64_t seed = 0;
  for (int i = 1; i < argc; ++i) {
    const std::string a = argv[i];
    if (a.rfind("-fuzz=", 0) == 0) fuzz_secs = atoll(a.c_str() + 6);
    else if (a.rfind("-runs=", 0) == 0) max_runs = atoll(a.c_str() + 6);
    else if (a.rfind("-max_len=", 0) == 0) max_len = size_t(atoll(a.c_str() + 9));
    else if (a.rfind("-seed=", 0) == 0) seed = uint64_t(atoll(a.c_str() + 6));
    else if (a.rfind("-timeout=", 0) == 0) g_timeout_s = unsigned(atoi(a.c_str() + 9));
    else if (a.rfind("-out=", 0) == 0) out_dir = a.substr(5);
    else if (a.rfind("-artifact=", 0) == 0)
      std::snprintf(g_artifact_prefix, sizeof(g_artifact_prefix), "%s",
                    a.c_str() + 10);
    else if (a == "-help" || a == "--help" || a[0] == '-') {
      std::fprintf(stderr,
                   "usage: %s [-fuzz=SECS] [-runs=N] [-max_len=N] "
                   "[-seed=N] [-timeout=SECS] [-artifact=PREFIX] "
                   "CORPUS_DIR|FILE...\n",
                   argv[0]);
      return a[0] == '-' && (a == "-help" || a == "--help") ? 0 : 2;
    } else {
      corpus_args.push_back(a);
    }
  }
  if (seed) g_rng = seed * 0x9E3779B97F4A7C15ull + 1;

  if (__sanitizer_set_death_callback)
    __sanitizer_set_death_callback(DumpCurrentInput);
  // SIGSEGV/SIGBUS stay with ASan (its report beats ours; the death
  // callback above still dumps the input). Our handlers cover the
  // paths ASan does not own: abort() from standalone UBSan, and the
  // per-input alarm. PTPU_FUZZ_ALL_SIGNALS=1 restores the old
  // behavior for uninstrumented builds.
  if (std::getenv("PTPU_FUZZ_ALL_SIGNALS")) {
    ::signal(SIGSEGV, CrashSignal);
    ::signal(SIGBUS, CrashSignal);
  }
  ::signal(SIGABRT, CrashSignal);
  ::signal(SIGALRM, CrashSignal);  // per-input timeout == finding

  if (LLVMFuzzerInitialize) LLVMFuzzerInitialize(&argc, &argv);

  std::vector<Input> corpus;
  for (const auto& a : corpus_args) LoadCorpus(a, &corpus);
  std::printf("ptpu_fuzz: %zu seed input(s), max_len %zu%s\n",
              corpus.size(), max_len,
              fuzz_secs || max_runs ? ", fuzzing" : ", replay only");

  // ---- replay every seed (also primes the coverage map) ----
  size_t replayed = 0;
  for (const auto& in : corpus) {
    RunOne(in.bytes.data(), in.bytes.size());
    ++replayed;
  }
  std::printf("ptpu_fuzz: replayed %zu input(s), cov %zu edge(s)\n",
              replayed, g_cov_count);
  if (!fuzz_secs && !max_runs) {
    std::printf("ptpu_fuzz: replay clean\n");
    return 0;
  }

  // ---- mutation loop ----
  const int64_t t_end = NowMs() + fuzz_secs * 1000;
  int64_t runs = 0, last_report = NowMs(), last_runs = 0;
  std::vector<uint8_t> buf;
  while ((fuzz_secs == 0 || NowMs() < t_end) &&
         (max_runs == 0 || runs < max_runs)) {
    if (!corpus.empty() && RndBelow(256) != 0) {
      buf = corpus[RndBelow(corpus.size())].bytes;
    } else {
      buf.assign(1 + RndBelow(64), 0);
      for (auto& c : buf) c = uint8_t(Rnd());
    }
    Mutate(&buf, max_len, corpus);
    const bool fresh = RunOne(buf.data(), buf.size());
    ++runs;
    if (fresh) {
      Input in;
      in.bytes = buf;
      corpus.push_back(std::move(in));
      if (!out_dir.empty()) WriteCorpusFile(out_dir, buf);
    }
    const int64_t now = NowMs();
    if (now - last_report >= 5000) {
      std::printf(
          "#%lld cov: %zu corp: %zu exec/s: %lld\n",
          (long long)runs, g_cov_count, corpus.size(),
          (long long)((runs - last_runs) * 1000 / (now - last_report)));
      std::fflush(stdout);
      last_report = now;
      last_runs = runs;
    }
  }
  std::printf("ptpu_fuzz: done — %lld run(s), cov %zu, corpus %zu\n",
              (long long)runs, g_cov_count, corpus.size());
  return 0;
}
