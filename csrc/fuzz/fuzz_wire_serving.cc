// Fuzz target: the serving wire-frame parser — SvServer::OnFrame in
// csrc/ptpu_serving.cc: v1 + traced-v2 INFER_REQ (per-input
// dtype/ndim/dims/raw walk), META, and the DECODE 0x65..0x6f ops
// (incl. the r13 speculative OPEN/STEP), through the real
// micro-batcher, bucket-ladder predictor run, draft/verify spec
// rounds, row-wise de-mux, and the KV session registry. Everything after the
// HMAC handshake is attacker-bytes; this is the full post-auth
// surface of the inference server.
//
// Harness shape: a REAL server (ptpu_serving_start2 over a
// hand-rolled matmul artifact + the selftest-convention decode
// artifact) whose internal OnFrame is reachable because this TU
// includes ptpu_serving.cc (the selftest idiom). Frames dispatch on a
// Detached net::Conn; batcher workers run and answer on it
// asynchronously — replies queue on the conn and die with it. The
// listener sockets are started but never dialed.
//
// Corpus: csrc/fuzz/corpus/wire_serving. Build: `make fuzz`.
#include "../ptpu_net.cc"
#include "../ptpu_trace.cc"
#include "../ptpu_predictor.cc"
#include "../ptpu_invar.cc"
#include "../ptpu_serving.cc"
#include "../ptpu_onnx_writer.h"

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

using ptpu::onnxw::onnx_node;
using ptpu::onnxw::onnx_node_iattr;
using ptpu::onnxw::onnx_tensor_f32;
using ptpu::onnxw::onnx_tensor_i64;
using ptpu::onnxw::onnx_value_info;
using ptpu::onnxw::put_lenf;

// y[B,2] = x[B,4] @ W[4,2] — batch-polymorphic, so the bucket ladder
// plans every size; runs are a few microseconds.
std::string build_matmul_model() {
  const float w[8] = {0.5f, -1.f, 2.f, 0.25f, 1.f, 0.f, -2.f, 3.f};
  std::string g;
  put_lenf(&g, 1, onnx_node("MatMul", {"x", "w"}, {"y"}));
  put_lenf(&g, 5, onnx_tensor_f32("w", {4, 2}, w, 8));
  put_lenf(&g, 11, onnx_value_info("x", 1, {2, 4}));
  put_lenf(&g, 12, onnx_value_info("y", 1, {2, 2}));
  std::string m;
  put_lenf(&m, 7, g);
  return m;
}

// The serving selftest's decode-step artifact convention (B=2, P=4,
// H=D=1): logit == running token sum.
std::string build_decode_model() {
  std::string g;
  put_lenf(&g, 1, onnx_node_iattr("Cast", {"ids"}, {"idsf"}, "to", 1));
  put_lenf(&g, 1, onnx_node("Reshape", {"idsf", "sh_nk"}, {"nk"}));
  put_lenf(&g, 1, onnx_node("Mul", {"nk", "two"}, {"nv"}));
  put_lenf(&g, 1, onnx_node("ReduceSum", {"k0", "axes"}, {"ksum"}));
  put_lenf(&g, 1, onnx_node("Reshape", {"ksum", "sh_y"}, {"ksum2"}));
  put_lenf(&g, 1, onnx_node_iattr("Cast", {"pos"}, {"posf"}, "to", 1));
  put_lenf(&g, 1, onnx_node("Reshape", {"posf", "sh_y"}, {"posr"}));
  put_lenf(&g, 1, onnx_node("Mul", {"posr", "zero"}, {"pos0"}));
  put_lenf(&g, 1, onnx_node("Add", {"ksum2", "idsf"}, {"t1"}));
  put_lenf(&g, 1, onnx_node("Add", {"t1", "pos0"}, {"y"}));
  put_lenf(&g, 5, onnx_tensor_i64("sh_nk", {4}, {2, 1, 1, 1}));
  put_lenf(&g, 5, onnx_tensor_i64("sh_y", {2}, {2, 1}));
  put_lenf(&g, 5, onnx_tensor_i64("axes", {3}, {1, 2, 3}));
  const float twov = 2.f, zerov = 0.f;
  put_lenf(&g, 5, onnx_tensor_f32("two", {}, &twov, 1));
  put_lenf(&g, 5, onnx_tensor_f32("zero", {}, &zerov, 1));
  put_lenf(&g, 11, onnx_value_info("ids", 7, {2, 1}));
  put_lenf(&g, 11, onnx_value_info("pos", 7, {2}));
  put_lenf(&g, 11, onnx_value_info("k0", 1, {2, 4, 1, 1}));
  put_lenf(&g, 11, onnx_value_info("v0", 1, {2, 4, 1, 1}));
  put_lenf(&g, 12, onnx_value_info("y", 1, {2, 1}));
  put_lenf(&g, 12, onnx_value_info("nk", 1, {2, 1, 1, 1}));
  put_lenf(&g, 12, onnx_value_info("nv", 1, {2, 1, 1, 1}));
  std::string m;
  put_lenf(&m, 7, g);
  return m;
}

// Width-2 sibling (the speculative VERIFY shape, kv_width == 2): per-
// window running sums via a lower-triangular cumsum matmul — same
// artifact the serving selftest's spec leg drives. Enabling the spec
// planes puts the whole DECODE_SPEC round machinery (draft bursts,
// width-2 verify, kv_trim rollback) behind the fuzzed parser.
std::string build_decode_model_w2() {
  std::string g;
  put_lenf(&g, 1, onnx_node_iattr("Cast", {"ids"}, {"idsf"}, "to", 1));
  put_lenf(&g, 1, onnx_node("Reshape", {"idsf", "sh_nk"}, {"nk"}));
  put_lenf(&g, 1, onnx_node("Mul", {"nk", "two"}, {"nv"}));
  put_lenf(&g, 1, onnx_node("MatMul", {"idsf", "tri"}, {"cum"}));
  put_lenf(&g, 1, onnx_node("ReduceSum", {"k0", "axes"}, {"ksum"}));
  put_lenf(&g, 1, onnx_node("Reshape", {"ksum", "sh_y"}, {"ksum2"}));
  put_lenf(&g, 1, onnx_node_iattr("Cast", {"pos"}, {"posf"}, "to", 1));
  put_lenf(&g, 1, onnx_node("Reshape", {"posf", "sh_y"}, {"posr"}));
  put_lenf(&g, 1, onnx_node("Mul", {"posr", "zero"}, {"pos0"}));
  put_lenf(&g, 1, onnx_node("Add", {"cum", "ksum2"}, {"t1"}));
  put_lenf(&g, 1, onnx_node("Add", {"t1", "pos0"}, {"y"}));
  put_lenf(&g, 5, onnx_tensor_i64("sh_nk", {4}, {2, 2, 1, 1}));
  put_lenf(&g, 5, onnx_tensor_i64("sh_y", {2}, {2, 1}));
  put_lenf(&g, 5, onnx_tensor_i64("axes", {3}, {1, 2, 3}));
  const float triv[4] = {1.f, 1.f, 0.f, 1.f};
  put_lenf(&g, 5, onnx_tensor_f32("tri", {2, 2}, triv, 4));
  const float twov = 2.f, zerov = 0.f;
  put_lenf(&g, 5, onnx_tensor_f32("two", {}, &twov, 1));
  put_lenf(&g, 5, onnx_tensor_f32("zero", {}, &zerov, 1));
  put_lenf(&g, 11, onnx_value_info("ids", 7, {2, 2}));
  put_lenf(&g, 11, onnx_value_info("pos", 7, {2}));
  put_lenf(&g, 11, onnx_value_info("k0", 1, {2, 4, 1, 1}));
  put_lenf(&g, 11, onnx_value_info("v0", 1, {2, 4, 1, 1}));
  put_lenf(&g, 12, onnx_value_info("y", 1, {2, 2}));
  put_lenf(&g, 12, onnx_value_info("nk", 1, {2, 2, 1, 1}));
  put_lenf(&g, 12, onnx_value_info("nv", 1, {2, 2, 1, 1}));
  std::string m;
  put_lenf(&m, 7, g);
  return m;
}

std::string write_tmp(const std::string& bytes, const char* name) {
  std::string path = std::string("/tmp/") + name;
  std::FILE* f = std::fopen(path.c_str(), "wb");
  if (!f) std::abort();
  std::fwrite(bytes.data(), 1, bytes.size(), f);
  std::fclose(f);
  return path;
}

SvServer* g_srv = nullptr;

void StopServer() {
  if (g_srv) ptpu_serving_stop(g_srv);
  g_srv = nullptr;
}

void InitOnce() {
  if (g_srv) return;
  // This harness injects frames on detached conns and throws replies
  // away (deferred requests are deleted mid-flight above), so the
  // request plane never quiesces and Stop()'s conservation gate
  // (ptpu_invar) would report req_balance noise — or abort under
  // PTPU_INVAR_FATAL=1. Not a counter bug: disable the gate here.
  setenv("PTPU_INVAR_OFF", "1", /*overwrite=*/1);
  const std::string mp =
      write_tmp(build_matmul_model(), "ptpu_fuzz_serving.onnx");
  const std::string dp =
      write_tmp(build_decode_model(), "ptpu_fuzz_decode.onnx");
  const std::string vp =
      write_tmp(build_decode_model_w2(), "ptpu_fuzz_verify.onnx");
  char err[512] = {0};
  g_srv = static_cast<SvServer*>(ptpu_serving_start4(
      mp.c_str(), dp.c_str(), /*spec_draft=*/dp.c_str(),
      /*spec_verify=*/vp.c_str(), /*port=*/0, "fz", 2, /*max_batch=*/4,
      /*deadline_us=*/200, /*instances=*/1, /*threads=*/1,
      /*loopback_only=*/1, /*kv_sessions=*/4, /*http_port=*/-1, err,
      sizeof(err)));
  if (!g_srv) {
    std::fprintf(stderr, "fuzz_wire_serving: start failed: %s\n", err);
    std::abort();
  }
  std::atexit(StopServer);  // teardown before LSan's end-of-run scan
}

}  // namespace

extern "C" int LLVMFuzzerTestOneInput(const uint8_t* data, size_t size) {
  if (size > (1u << 20)) return 0;
  InitOnce();
  // Replay at every misalignment 0..7 (ISSUE 17): the parser reads
  // payloads in place in the reassembly buffer, where a frame lands
  // at whatever offset the preceding stream left — the unaligned-safe
  // codecs must hold (under ASan/UBSan) at every shift.
  std::vector<uint8_t> shifted(size + 8);
  for (size_t s = 0; s < 8; ++s) {
    if (size) std::memcpy(shifted.data() + s, data, size);
    auto conn = ptpu::net::Conn::Detached();
    (void)g_srv->OnFrame(conn, shifted.data() + s, uint32_t(size));
    // a kDefer stash is normally freed by the net core's on_close
    // hook; a Detached conn has no loop, so mirror that hook here
    delete static_cast<SvRequest*>(conn->user);
    conn->user = nullptr;
    g_srv->DecodeConnClosed(conn.get());
  }
  return 0;
}
