// Fuzz target: the capture-file parser — ptpu::capture::
// ParseCaptureBytes in csrc/ptpu_capture.h (header + record array,
// the ptpu_drill harness). Capture files are UNTRUSTED DISK INPUT:
// tools/drill_replay.py writes them, operators copy them between
// machines, and anything on the capture path can feed stale or
// corrupt bytes back into the replay pipeline — so the parser gets
// the same treatment as the tune cache: bounds-checked, fuzzed, and
// every malformed shape is a whole-file reject (kMalformed), never a
// crash or a partial adopt.
//
// Harness shape: bytes in, ParseCaptureBytes. Well-formed inputs
// additionally round-trip through SerializeCapture and must re-parse
// identically (same count, same record fields, same payload bytes) —
// canonicalization bugs abort here instead of silently rewriting a
// drill capture. The Python twin of both directions lives in
// tools/drill_replay.py; tools/ptpu_check.py pins the two layouts
// together.
//
// Corpus: csrc/fuzz/corpus/capture (valid files, truncations, huge
// counts, ver/tag-vs-payload mismatches — csrc/fuzz/gen_seeds.py).
// Build: `make fuzz`.
#include "../ptpu_capture.h"

#include <cassert>
#include <cstdint>
#include <cstring>
#include <vector>

extern "C" int LLVMFuzzerTestOneInput(const uint8_t* data, size_t size) {
  if (size > (1u << 20)) return 0;
  namespace cp = ptpu::capture;
  std::vector<cp::CapRecord> out;
  const cp::ParseResult r = cp::ParseCaptureBytes(data, size, &out);
  if (r != cp::ParseResult::kOk) return 0;
  // canonical round trip: serialize the parsed records and re-parse
  std::vector<uint8_t> bytes;
  cp::SerializeCapture(out, &bytes);
  std::vector<cp::CapRecord> again;
  const cp::ParseResult r2 =
      cp::ParseCaptureBytes(bytes.data(), bytes.size(), &again);
  assert(r2 == cp::ParseResult::kOk);
  assert(again.size() == out.size());
  for (size_t i = 0; i < out.size(); ++i) {
    assert(again[i].ts_us == out[i].ts_us);
    assert(again[i].conn == out[i].conn);
    assert(again[i].frame_len == out[i].frame_len);
    assert(again[i].ver == out[i].ver);
    assert(again[i].tag == out[i].tag);
    assert(again[i].payload == out[i].payload);
  }
  return 0;
}
