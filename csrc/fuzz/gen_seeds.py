#!/usr/bin/env python3
"""Structure-aware seed corpora for the csrc/fuzz harnesses (ISSUE 11).

Regenerates csrc/fuzz/corpus/<target>/seed-*.bin from the SAME frame
layouts the selftests hand-roll (wire.py / serving.py twins) plus a
tiny ONNX/protobuf writer mirroring csrc/ptpu_onnx_writer.h. The
corpus is CHECKED IN — this script exists so seeds can be rebuilt
when a layout changes; crash regressions (crash-*.bin) are never
regenerated, they are frozen findings.

The all-ops ONNX seed derives the op list from ptpu_predictor.cc
itself (the same extraction tools/ptpu_check.py's `fuzz` checker
uses), so a newly parsed op automatically lands in the corpus on the
next regen — and the checker fails until it does.

Usage: python3 csrc/fuzz/gen_seeds.py   (idempotent, writes in place)
"""
import os
import re
import struct
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
CSRC = os.path.dirname(HERE)


def w(target, name, data):
    d = os.path.join(HERE, "corpus", target)
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, name), "wb") as f:
        f.write(data)


# ---------------------------------------------------------------------------
# tiny protobuf writer (twin of csrc/ptpu_onnx_writer.h)
# ---------------------------------------------------------------------------

def varint(v):
    out = b""
    while v >= 0x80:
        out += bytes([v & 0x7F | 0x80])
        v >>= 7
    return out + bytes([v])


def tag(field, wire):
    return varint(field << 3 | wire)


def u64f(field, v):
    return tag(field, 0) + varint(v)


def lenf(field, payload):
    return tag(field, 2) + varint(len(payload)) + payload


def onnx_tensor_f32(name, dims, vals):
    t = b"".join(u64f(1, d) for d in dims) + u64f(2, 1)
    t += lenf(8, name.encode())
    t += lenf(9, struct.pack(f"<{len(vals)}f", *vals))
    return t


def onnx_tensor_i64(name, dims, vals):
    t = b"".join(u64f(1, d) for d in dims) + u64f(2, 7)
    t += lenf(8, name.encode())
    t += lenf(9, struct.pack(f"<{len(vals)}q", *vals))
    return t


def onnx_value_info(name, elem, dims):
    shape = b"".join(lenf(1, u64f(1, d)) for d in dims)
    tt = u64f(1, elem) + lenf(2, shape)
    return lenf(1, name.encode()) + lenf(2, lenf(1, tt))


def onnx_node(op, ins, outs, iattr=None):
    n = b"".join(lenf(1, i.encode()) for i in ins)
    n += b"".join(lenf(2, o.encode()) for o in outs)
    n += lenf(4, op.encode())
    if iattr:
        aname, aval = iattr
        n += lenf(5, lenf(1, aname.encode()) + u64f(3, aval))
    return n


def onnx_model(graph_fields):
    return lenf(7, b"".join(graph_fields))


def matmul_model():
    # twin of fuzz_wire_serving.cc build_matmul_model (y = x[B,4] @ w)
    g = [
        lenf(1, onnx_node("MatMul", ["x", "w"], ["y"])),
        lenf(5, onnx_tensor_f32(
            "w", [4, 2], [0.5, -1.0, 2.0, 0.25, 1.0, 0.0, -2.0, 3.0])),
        lenf(11, onnx_value_info("x", 1, [2, 4])),
        lenf(12, onnx_value_info("y", 1, [2, 2])),
    ]
    return onnx_model(g)


def decode_model():
    # twin of the serving-selftest decode artifact (B=2, P=4, H=D=1)
    g = [
        lenf(1, onnx_node("Cast", ["ids"], ["idsf"], ("to", 1))),
        lenf(1, onnx_node("Reshape", ["idsf", "sh_nk"], ["nk"])),
        lenf(1, onnx_node("Mul", ["nk", "two"], ["nv"])),
        lenf(1, onnx_node("ReduceSum", ["k0", "axes"], ["ksum"])),
        lenf(1, onnx_node("Reshape", ["ksum", "sh_y"], ["ksum2"])),
        lenf(1, onnx_node("Cast", ["pos"], ["posf"], ("to", 1))),
        lenf(1, onnx_node("Reshape", ["posf", "sh_y"], ["posr"])),
        lenf(1, onnx_node("Mul", ["posr", "zero"], ["pos0"])),
        lenf(1, onnx_node("Add", ["ksum2", "idsf"], ["t1"])),
        lenf(1, onnx_node("Add", ["t1", "pos0"], ["y"])),
        lenf(5, onnx_tensor_i64("sh_nk", [4], [2, 1, 1, 1])),
        lenf(5, onnx_tensor_i64("sh_y", [2], [2, 1])),
        lenf(5, onnx_tensor_i64("axes", [3], [1, 2, 3])),
        lenf(5, onnx_tensor_f32("two", [], [2.0])),
        lenf(5, onnx_tensor_f32("zero", [], [0.0])),
        lenf(11, onnx_value_info("ids", 7, [2, 1])),
        lenf(11, onnx_value_info("pos", 7, [2])),
        lenf(11, onnx_value_info("k0", 1, [2, 4, 1, 1])),
        lenf(11, onnx_value_info("v0", 1, [2, 4, 1, 1])),
        lenf(12, onnx_value_info("y", 1, [2, 1])),
        lenf(12, onnx_value_info("nk", 1, [2, 1, 1, 1])),
        lenf(12, onnx_value_info("nv", 1, [2, 1, 1, 1])),
    ]
    return onnx_model(g)


def predictor_ops():
    """Every op name ptpu_predictor.cc dispatches on — the extraction
    tools/ptpu_check.py's `fuzz` checker mirrors."""
    src = open(os.path.join(CSRC, "ptpu_predictor.cc"),
               encoding="utf-8").read()
    ops = set(re.findall(r'\bop == "([A-Z][A-Za-z0-9]*)"', src))
    ops |= set(re.findall(r'\.op == "([A-Z][A-Za-z0-9]*)"', src))
    # bin_code / un_code map literals: {"Add", B_ADD} etc.
    ops |= set(re.findall(r'\{"([A-Z][A-Za-z0-9]*)",\s*[BU]_[A-Z0-9_]+\}',
                          src))
    return sorted(ops)


def all_ops_model():
    """One (invalid but parseable) graph holding a node of EVERY op the
    predictor knows: parser/validator coverage + the corpus bytes the
    `fuzz` checker requires per op."""
    g = []
    for k, op in enumerate(predictor_ops()):
        g.append(lenf(1, onnx_node(op, [f"i{k}", f"j{k}"], [f"o{k}"])))
    g.append(lenf(5, onnx_tensor_f32("i0", [2], [1.0, 2.0])))
    g.append(lenf(11, onnx_value_info("x", 1, [1, 2])))
    g.append(lenf(12, onnx_value_info("o0", 1, [1, 2])))
    return onnx_model(g)


# ---------------------------------------------------------------------------
# wire frames (payloads only — the u32 length prefix is the net
# core's, handlers never see it)
# ---------------------------------------------------------------------------

def ps_pull(table=b"t", ids=(0, 1, 2, 63), ver=1, tid=None):
    f = bytes([ver, 0x50])
    if tid is not None:
        f += struct.pack("<Q", tid)
    f += bytes([len(table)]) + table
    f += struct.pack("<I", len(ids)) + struct.pack(f"<{len(ids)}q", *ids)
    return f


def ps_push(table=b"t", ids=(1, 2, 1), dim=4, flags=0, ver=1, tid=None):
    f = bytes([ver, 0x52])
    if tid is not None:
        f += struct.pack("<Q", tid)
    f += bytes([len(table)]) + table
    f += bytes([flags]) + struct.pack("<II", len(ids), dim)
    f += struct.pack(f"<{len(ids)}q", *ids)
    f += struct.pack(f"<{len(ids) * dim}f",
                     *([0.25] * (len(ids) * dim)))
    return f


def sv_infer(rid=7, rows=1, ver=1, tid=None, dtype=1, tail=4):
    f = bytes([ver, 0x60])
    if tid is not None:
        f += struct.pack("<Q", tid)
    f += struct.pack("<QH", rid, 1)  # one input
    f += bytes([dtype, 2]) + struct.pack("<qq", rows, tail)
    f += struct.pack(f"<{rows * tail}f", *([1.5] * (rows * tail)))
    return f


def sv_plain(tag_byte, *fields, ver=1, tid=None):
    f = bytes([ver, tag_byte])
    if tid is not None:
        f += struct.pack("<Q", tid)
    for v in fields:
        f += struct.pack("<Q", v)
    return f


def frame(payload):
    return struct.pack("<I", len(payload)) + payload


# ---------------------------------------------------------------------------
# tuning-cache files (twin of ptpu_tune.h: "PTUN" header + 44-byte
# records; the fuzz_tune harness reads the expected cpu signature out
# of bytes 8..15, so any well-formed file parses kOk regardless of
# the generating machine)
# ---------------------------------------------------------------------------

TUNE_MAGIC = 0x4E555450  # "PTUN" little-endian


def tune_rec(m=4, n=512, k=128, dtype=0, path=0, kc=320, mult=3, group=0):
    return struct.pack("<qqqIiiii", m, n, k, dtype, path, kc, mult, group)


def tune_cache(recs, magic=TUNE_MAGIC, version=1, sig=0x1122334455667788,
               count=None):
    body = b"".join(recs)
    n = len(recs) if count is None else count
    return struct.pack("<IIQI", magic, version, sig, n) + body


# ---------------------------------------------------------------------------
# capture files (twin of ptpu_capture.h: "PCAP" header + 28-byte
# records + per-record payload; tools/drill_replay.py carries the
# SAME constants and tools/ptpu_check.py pins them together)
# ---------------------------------------------------------------------------

CAPTURE_MAGIC = 0x50414350  # "PCAP" little-endian


def capture_rec(ts=1000, conn=7, payload=b"\x01\x60" + b"\x00" * 10,
                frame_len=None, ver=None, tag=None, reserved=0):
    flen = len(payload) if frame_len is None else frame_len
    v = (payload[0] if len(payload) >= 1 else 0) if ver is None else ver
    t = (payload[1] if len(payload) >= 2 else 0) if tag is None else tag
    return struct.pack("<qQIIBBH", ts, conn, flen, len(payload),
                       v, t, reserved) + payload


def capture_file(recs, magic=CAPTURE_MAGIC, version=1, count=None,
                 body=None):
    blob = b"".join(recs)
    n = len(recs) if count is None else count
    b = len(blob) if body is None else body
    return struct.pack("<IIII", magic, version, n, b) + blob


# ---------------------------------------------------------------------------
# KV spill-tier files (twin of ptpu_spill.h: "PSPL" spill-file header,
# "PHIB" hibernation records, "PPFX" prefix-persist files — the r19
# tiering formats; tools/ptpu_check.py pins these magics to the C
# constants and csrc/fuzz/fuzz_spill.cc fuzzes all three parsers)
# ---------------------------------------------------------------------------

SPILL_MAGIC = 0x4C505350   # "PSPL" little-endian
HIB_MAGIC = 0x42494850     # "PHIB" little-endian
PREFIX_MAGIC = 0x58465050  # "PPFX" little-endian


def spill_header(page=2, layers=1, heads=2, hdim=4, slot_bytes=None,
                 magic=SPILL_MAGIC, version=1):
    sb = (layers * 2 * page * heads * hdim * 4 if slot_bytes is None
          else slot_bytes)
    return struct.pack("<IIIIIIQ", magic, version, page, layers, heads,
                       hdim, sb) + b"\x00" * 4  # 8 spare bytes (32 total)


def hib_group(kind=1, a=0, b=0):
    return struct.pack("<IIqQ", kind, 0, a, b)


def hib_rec(groups, hib_id=1, length=32, magic=HIB_MAGIC, version=1,
            count=None, reserved=0):
    n = len(groups) if count is None else count
    return struct.pack("<IIQQII", magic, version, hib_id, length, n,
                       reserved) + b"".join(groups)


def fnv1a(data):
    h = 0xCBF29CE484222325
    for c in data:
        h = ((h ^ c) * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h


def prefix_rec(page, layers, heads, hdim, parent=0xFFFFFFFF, toks=None,
               val=1.0, checksum=None, ntoks=None):
    elems = layers * 2 * page * heads * hdim
    t = list(range(1, page + 1)) if toks is None else toks
    body = struct.pack("<II", parent, page if ntoks is None else ntoks)
    body += struct.pack(f"<{page}q", *t)
    body += struct.pack(f"<{elems}f", *([val] * elems))
    ck = fnv1a(body) if checksum is None else checksum
    return body + struct.pack("<Q", ck)


def prefix_file(recs, page=2, layers=1, heads=2, hdim=4,
                magic=PREFIX_MAGIC, version=1, count=None, reserved=0):
    n = len(recs) if count is None else count
    return struct.pack("<IIIIIIII", magic, version, page, layers, heads,
                       hdim, n, reserved) + b"".join(recs)


def main():
    # ---- wire_ps ----
    w("wire_ps", "seed-pull-v1.bin", ps_pull())
    w("wire_ps", "seed-pull-v2-traced.bin", ps_pull(ver=2, tid=0xABCDEF))
    w("wire_ps", "seed-pull-emb-offset.bin",
      ps_pull(table=b"emb", ids=(1000, 1031)))
    w("wire_ps", "seed-pull-unknown-table.bin", ps_pull(table=b"nope"))
    w("wire_ps", "seed-pull-out-of-range.bin", ps_pull(ids=(64,)))
    w("wire_ps", "seed-push-v1.bin", ps_push())
    w("wire_ps", "seed-push-v2-traced.bin", ps_push(ver=2, tid=5))
    w("wire_ps", "seed-push-async.bin", ps_push(flags=1))
    w("wire_ps", "seed-push-empty.bin",
      ps_push(ids=(), dim=0))
    w("wire_ps", "seed-push-dim-mismatch.bin", ps_push(dim=3))
    # reply-direction tags arriving as requests: parser must reject
    w("wire_ps", "seed-tag-pull-rep.bin", bytes([1, 0x51]) + b"\0" * 8)
    w("wire_ps", "seed-tag-ok.bin", bytes([1, 0x53]))
    w("wire_ps", "seed-tag-err.bin",
      bytes([1, 0x54]) + struct.pack("<I", 3) + b"boo")
    w("wire_ps", "seed-truncated.bin", ps_pull()[:9])
    w("wire_ps", "seed-bad-version.bin", bytes([9, 0x50]) + b"\x01t")
    # in-place parse misalignment sweep (ISSUE 17): the table-name
    # length shifts every i64 id / f32 val offset, so names of length
    # 1..8 land the PULL id block (payload offset 7 + len) at every
    # misalignment 0..7 — the harness additionally replays each seed
    # at buffer shifts 0..7, covering the full cross product
    for k in range(1, 9):
        name = b"t" + b"x" * (k - 1)
        w("wire_ps", f"seed-pull-misalign-{k - 1}.bin",
          ps_pull(table=name, ids=(0, 1)))
        w("wire_ps", f"seed-push-misalign-{k - 1}.bin",
          ps_push(table=name, ids=(1, 2), dim=4))

    # ---- wire_serving ----
    w("wire_serving", "seed-meta.bin", sv_plain(0x63))
    w("wire_serving", "seed-meta-v2.bin", sv_plain(0x63, ver=2, tid=9))
    w("wire_serving", "seed-infer-b1.bin", sv_infer())
    w("wire_serving", "seed-infer-b2.bin", sv_infer(rows=2))
    w("wire_serving", "seed-infer-v2-traced.bin",
      sv_infer(ver=2, tid=0x1122334455667788))
    w("wire_serving", "seed-infer-bad-dtype.bin", sv_infer(dtype=7))
    w("wire_serving", "seed-infer-bad-tail.bin", sv_infer(tail=5))
    w("wire_serving", "seed-infer-trunc.bin", sv_infer()[:14])
    w("wire_serving", "seed-decode-open.bin", sv_plain(0x65, 11))
    w("wire_serving", "seed-decode-open-v2.bin",
      sv_plain(0x65, 12, ver=2, tid=3))
    w("wire_serving", "seed-decode-step.bin", sv_plain(0x67, 13, 1, 5))
    w("wire_serving", "seed-decode-step-v2.bin",
      sv_plain(0x67, 14, 1, 6, ver=2, tid=4))
    w("wire_serving", "seed-decode-close.bin", sv_plain(0x69, 15, 1))
    w("wire_serving", "seed-decode-unknown-sess.bin",
      sv_plain(0x67, 16, 999999, 0))
    # paged-engine ops (r12): OPEN2 prompt prefill + COW fork
    def sv_open2(rid, toks, flags=0, ver=1, tid=None, trunc=None):
        f = bytes([ver, 0x6a])
        if tid is not None:
            f += struct.pack("<Q", tid)
        f += struct.pack("<QII", rid, len(toks), flags)
        f += struct.pack(f"<{len(toks)}q", *toks)
        return f if trunc is None else f[:trunc]
    w("wire_serving", "seed-decode-open2.bin", sv_open2(21, (5, 6, 7)))
    w("wire_serving", "seed-decode-open2-v2.bin",
      sv_open2(22, (5, 6), ver=2, tid=8))
    w("wire_serving", "seed-decode-open2-flags.bin",
      sv_open2(23, (5,), flags=1))
    w("wire_serving", "seed-decode-open2-trunc.bin",
      sv_open2(24, (5, 6, 7, 8), trunc=22))
    w("wire_serving", "seed-decode-open2-huge-n.bin",
      bytes([1, 0x6a]) + struct.pack("<QII", 25, 0xFFFFFFFF, 0))
    w("wire_serving", "seed-decode-fork.bin", sv_plain(0x6c, 26, 1))
    w("wire_serving", "seed-decode-fork-v2.bin",
      sv_plain(0x6c, 27, 999999, ver=2, tid=6))
    # speculative-decoding ops (r13): SPEC_OPEN carries
    # [u32 n][u32 flags][u64 seed][n x i64]; SPEC_STEP is
    # [u64 rid][u64 session]
    def sv_spec_open(rid, toks, flags=0, seed=0, ver=1, tid=None,
                     trunc=None):
        f = bytes([ver, 0x6d])
        if tid is not None:
            f += struct.pack("<Q", tid)
        f += struct.pack("<QIIQ", rid, len(toks), flags, seed)
        f += struct.pack(f"<{len(toks)}q", *toks)
        return f if trunc is None else f[:trunc]
    w("wire_serving", "seed-spec-open.bin",
      sv_spec_open(31, (5, 6, 7), seed=11))
    w("wire_serving", "seed-spec-open-v2.bin",
      sv_spec_open(32, (5, 6), flags=1, seed=12, ver=2, tid=9))
    w("wire_serving", "seed-spec-open-trunc.bin",
      sv_spec_open(33, (5, 6, 7, 8), trunc=30))
    w("wire_serving", "seed-spec-open-huge-n.bin",
      bytes([1, 0x6d]) + struct.pack("<QIIQ", 34, 0xFFFFFFFF, 0, 0))
    w("wire_serving", "seed-spec-open-bad-flags.bin",
      sv_spec_open(35, (5,), flags=0xFF))
    w("wire_serving", "seed-spec-step.bin", sv_plain(0x6e, 36, 1))
    w("wire_serving", "seed-spec-step-v2.bin",
      sv_plain(0x6e, 37, 999999, ver=2, tid=10))
    # reply-direction tag as request: rejected
    w("wire_serving", "seed-tag-spec-rep.bin",
      bytes([1, 0x6f]) + struct.pack("<QQII", 1, 2, 0, 1) +
      struct.pack("<q", 0))
    # reply-direction tag as request: rejected
    w("wire_serving", "seed-tag-decode-open-rep.bin",
      bytes([1, 0x6b]) + struct.pack("<QQII", 1, 2, 0, 1) +
      struct.pack("<f", 0.0))
    # reply-direction tags as requests: rejected
    w("wire_serving", "seed-tag-infer-rep.bin", sv_plain(0x61, 1))
    w("wire_serving", "seed-tag-infer-err.bin",
      bytes([1, 0x62]) + struct.pack("<QI", 1, 2) + b"xx")
    w("wire_serving", "seed-tag-meta-rep.bin",
      bytes([1, 0x64]) + struct.pack("<I", 2) + b"{}")
    w("wire_serving", "seed-tag-decode-sess.bin", sv_plain(0x66, 1, 2))
    w("wire_serving", "seed-tag-decode-rep.bin",
      bytes([1, 0x68]) + struct.pack("<QQI", 1, 2, 1) +
      struct.pack("<f", 0.0))
    w("wire_serving", "seed-bad-version.bin", bytes([7, 0x60]))
    # in-place ingestion seeds (ISSUE 17): the parser borrows views
    # straight out of the reassembly buffer — multi-row payloads walk
    # the borrowed region end to end, and the v1/v2 pair shifts every
    # body offset by the 8-byte trace ext (the harness replays each
    # at buffer shifts 0..7)
    w("wire_serving", "seed-infer-b4.bin", sv_infer(rows=4))
    w("wire_serving", "seed-infer-b4-v2.bin",
      sv_infer(rows=4, ver=2, tid=0x77))
    w("wire_serving", "seed-infer-short-payload.bin",
      sv_infer(rows=2)[:-3])

    # ---- http ----
    def req(line, hdrs=b"Host: x\r\n"):
        return line + b"\r\n" + hdrs + b"\r\n"
    w("http", "seed-healthz.bin", req(b"GET /healthz HTTP/1.1"))
    w("http", "seed-statsz.bin", req(b"GET /statsz HTTP/1.1"))
    w("http", "seed-metrics.bin", req(b"GET /metrics HTTP/1.1"))
    w("http", "seed-tracez.bin", req(b"GET /tracez?n=5 HTTP/1.1"))
    w("http", "seed-tracez-multi-key.bin",
      req(b"GET /tracez?conn=1&n=2 HTTP/1.1"))
    w("http", "seed-capturez.bin", req(b"GET /capturez?n=5 HTTP/1.1"))
    w("http", "seed-invarz.bin", req(b"GET /invarz HTTP/1.1"))
    w("http", "seed-404.bin", req(b"GET /nope HTTP/1.1"))
    w("http", "seed-post.bin", req(b"POST /healthz HTTP/1.1"))
    w("http", "seed-http10-keepalive.bin",
      req(b"GET /healthz HTTP/1.0",
          b"Connection: keep-alive\r\n"))
    w("http", "seed-connection-close.bin",
      req(b"GET /statsz HTTP/1.1", b"Connection: close\r\n"))
    w("http", "seed-bad-line.bin", req(b"GARBAGE"))
    w("http", "seed-partial.bin", b"GET /heal")
    w("http", "seed-empty-target.bin", req(b"GET  HTTP/1.1"))

    # ---- onnx ----
    w("onnx", "seed-matmul.bin", matmul_model())
    w("onnx", "seed-decode.bin", decode_model())
    w("onnx", "seed-all-ops.bin", all_ops_model())
    w("onnx", "seed-trunc.bin", matmul_model()[:21])
    w("onnx", "seed-empty-graph.bin", onnx_model([]))
    w("onnx", "seed-not-proto.bin", b"\xff\xfe\x00garbage")

    # ---- json (PromFromStatsJson walker) ----
    w("json", "seed-serving-stats.bin", (
        b'{"server":{"requests":5,"replies":5,"conns_active":1},'
        b'"batcher":{"batches":2,"queue_depth":{"count":3,"sum":4,'
        b'"buckets":[1,2]},"e2e_us":{"count":1,"sum":9,"buckets":[1]}},'
        b'"decode":{"opens":1,"sessions_active":0}}'))
    w("json", "seed-ps-stats.bin", (
        b'{"server":{"pull_ops":7,"pull_us":{"count":2,"sum":10,'
        b'"buckets":[1,1,0]}},"tables":{"emb":{"wire":{"bytes_in":3},'
        b'"table":{"rows":64}}}}'))
    # invariant reports (r20): the /invarz body shape — the walker must
    # render the nested violations object, and fuzz_json additionally
    # feeds every input through ptpu::invar::ViolationCount
    w("json", "seed-invar-clean.bin", (
        b'{"enabled":1,"plane":"serving","checked":9,"skipped":2,'
        b'"violations":{}}'))
    w("json", "seed-invar-violated.bin", (
        b'{"enabled":1,"plane":"ps","checked":3,"skipped":8,'
        b'"violations":{"req_balance":{"law":"server.requests == '
        b'server.replies + server.req_errors","detail":"lhs=5 rhs=4"},'
        b'"conn_balance":{"law":"x == y","detail":"lhs=1 rhs=0"}}}'))
    w("json", "seed-invar-disabled.bin",
      b'{"enabled":0,"plane":"serving","violations":{}}')
    w("json", "seed-escapes.bin",
      b'{"a\\n\\t\\"b\\\\":1,"c":{"d\\r":2}}')
    w("json", "seed-deep.bin",
      b'{"a":' * 20 + b"1" + b"}" * 20)
    w("json", "seed-arrays.bin", b'{"x":[1,2,3],"y":[],"z":[0]}')
    w("json", "seed-bad.bin", b'{"a":,}')
    w("json", "seed-empty.bin", b"")

    # ---- frames (leading byte odd == authenticate first) ----
    w("frames", "seed-auth-echo.bin", b"\x01" + frame(b"hello"))
    w("frames", "seed-auth-pipelined.bin",
      b"\x01" + frame(b"a") + frame(b"bb") + frame(b"ccc"))
    w("frames", "seed-auth-defer.bin", b"\x01" + frame(b"Rdefer"))
    w("frames", "seed-auth-close.bin", b"\x01" + frame(b"Xbye"))
    w("frames", "seed-auth-empty-frame.bin", b"\x01" + frame(b""))
    w("frames", "seed-auth-oversize.bin",
      b"\x01" + struct.pack("<I", (1 << 20) + 1) + b"zz")
    w("frames", "seed-preauth-badmac.bin",
      b"\x00" + frame(b"\x00" * 32))
    w("frames", "seed-preauth-wrong-len.bin",
      b"\x00" + frame(b"\x00" * 31))
    w("frames", "seed-preauth-huge-claim.bin",
      b"\x00" + struct.pack("<I", 0x7FFFFFFF))
    w("frames", "seed-preauth-partial.bin", b"\x00\x05\x00")
    # reassembly misalignment + split seeds (ISSUE 17): a k-byte pad
    # frame ahead of the echo frame lands the second payload at every
    # in-buffer misalignment 0..7; the harness's split point is
    # derived from the first body byte (the pad frame's length low
    # byte == k), so the two-write seam also sweeps across the length
    # prefix and payload of the second frame as k varies
    for k in range(8):
        w("frames", f"seed-auth-misalign-{k}.bin",
          b"\x01" + frame(b"p" * k) + frame(b"hello"))

    # ---- tune (persisted autotuning cache, ISSUE 16) ----
    w("tune", "seed-valid.bin", tune_cache([
        tune_rec(),                                   # f32 macro default
        tune_rec(m=2, path=1, kc=160, mult=2),        # f32 row-GEMV alt
        tune_rec(m=0, n=64, k=96, dtype=2, group=32),  # q4 pack group
    ]))
    w("tune", "seed-empty.bin", tune_cache([]))
    w("tune", "seed-one-q4.bin",
      tune_cache([tune_rec(m=1, n=4096, k=4096, dtype=1, group=128)]))
    w("tune", "seed-trunc-header.bin", tune_cache([])[:11])
    w("tune", "seed-trunc-record.bin",
      tune_cache([tune_rec(), tune_rec(m=8)])[:-7])
    w("tune", "seed-padded.bin", tune_cache([tune_rec()]) + b"\x00")
    w("tune", "seed-huge-count.bin",
      tune_cache([tune_rec()], count=0xFFFFFFFF))
    w("tune", "seed-count-over-cap.bin",
      tune_cache([tune_rec(m=i) for i in range(8)], count=4097))
    w("tune", "seed-bad-magic.bin",
      tune_cache([tune_rec()], magic=0x4E555451))
    w("tune", "seed-bad-version.bin",
      tune_cache([tune_rec()], version=9))
    # alien signature: the harness still parses it with the embedded
    # sig (kOk) AND a flipped sig (kWrongCpu) every exec
    w("tune", "seed-alien-sig.bin",
      tune_cache([tune_rec()], sig=0xDEADBEEFCAFEF00D))
    # out-of-range fields: one bad record poisons the whole file
    w("tune", "seed-bad-group.bin",
      tune_cache([tune_rec(), tune_rec(dtype=2, group=99999)]))
    w("tune", "seed-bad-path.bin", tune_cache([tune_rec(path=7)]))
    w("tune", "seed-overflow-dims.bin",
      tune_cache([tune_rec(m=1 << 50, n=-3)]))
    w("tune", "seed-bad-dtype.bin", tune_cache([tune_rec(dtype=9)]))

    # ---- capture (ptpu_drill raw-frame capture files) ----
    w("capture", "seed-valid.bin", capture_file([
        capture_rec(),                                  # infer-ish
        capture_rec(ts=2000, conn=8, payload=b"\x01\x63"),   # meta
        capture_rec(ts=3000, conn=7, payload=b"\x02\x60" + b"\x11" * 16),
    ]))
    w("capture", "seed-empty.bin", capture_file([]))
    w("capture", "seed-truncated-tail.bin",
      capture_file([capture_rec(frame_len=512)]))      # cap < frame
    w("capture", "seed-one-byte-payload.bin",
      capture_file([capture_rec(payload=b"\x01")]))
    w("capture", "seed-empty-payload.bin",
      capture_file([capture_rec(payload=b"", frame_len=64)]))
    w("capture", "seed-trunc-header.bin", capture_file([])[:11])
    w("capture", "seed-trunc-record.bin",
      capture_file([capture_rec(), capture_rec(conn=9)])[:-5])
    w("capture", "seed-padded.bin",
      capture_file([capture_rec()]) + b"\x00")
    w("capture", "seed-huge-count.bin",
      capture_file([capture_rec()], count=0xFFFFFFFF))
    w("capture", "seed-count-over-cap.bin",
      capture_file([capture_rec()], count=65537))
    w("capture", "seed-body-lies.bin",
      capture_file([capture_rec()], body=4))
    w("capture", "seed-bad-magic.bin",
      capture_file([capture_rec()], magic=0x50414351))
    w("capture", "seed-bad-version.bin",
      capture_file([capture_rec()], version=9))
    # the mirrored ver/tag fields must MATCH payload[0]/payload[1]
    w("capture", "seed-ver-mismatch.bin",
      capture_file([capture_rec(ver=9)]))
    w("capture", "seed-tag-mismatch.bin",
      capture_file([capture_rec(tag=0x99)]))
    w("capture", "seed-reserved-set.bin",
      capture_file([capture_rec(reserved=1)]))
    w("capture", "seed-cap-over-max.bin",
      capture_file([capture_rec(payload=b"\x01\x60" + b"z" * 4095)]))

    # ---- spill (r19 KV tiering: spill header + hibernation records +
    # prefix-persist files; one corpus for all three parsers — the
    # magics disambiguate inside fuzz_spill.cc) ----
    w("spill", "seed-spill-valid.bin", spill_header())
    w("spill", "seed-spill-trunc.bin", spill_header()[:17])
    w("spill", "seed-spill-bad-magic.bin",
      spill_header(magic=0x4C505351))
    w("spill", "seed-spill-bad-version.bin", spill_header(version=9))
    w("spill", "seed-spill-geom-lies.bin",
      spill_header(slot_bytes=12345))         # != layers*2*P*H*D*4
    w("spill", "seed-spill-geom-zero.bin", spill_header(page=0))
    w("spill", "seed-spill-geom-over-cap.bin",
      spill_header(page=1 << 20, slot_bytes=1))
    w("spill", "seed-hib-valid.bin", hib_rec([
        hib_group(kind=1, a=0),               # spilled slot 0
        hib_group(kind=0, a=3, b=7),          # shared gid 3 gen 7
        hib_group(kind=1, a=2),
    ]))
    w("spill", "seed-hib-empty.bin", hib_rec([], length=0))
    w("spill", "seed-hib-trunc-header.bin", hib_rec([])[:13])
    w("spill", "seed-hib-trunc-record.bin",
      hib_rec([hib_group(), hib_group(a=1)])[:-9])
    w("spill", "seed-hib-padded.bin", hib_rec([hib_group()]) + b"\x00")
    w("spill", "seed-hib-huge-count.bin",
      hib_rec([hib_group()], count=0xFFFFFFFF))
    w("spill", "seed-hib-count-over-cap.bin",
      hib_rec([hib_group()], count=(1 << 20) + 1))
    w("spill", "seed-hib-bad-magic.bin",
      hib_rec([hib_group()], magic=0x42494851))
    w("spill", "seed-hib-bad-version.bin",
      hib_rec([hib_group()], version=9))
    w("spill", "seed-hib-bad-kind.bin", hib_rec([hib_group(kind=2)]))
    w("spill", "seed-hib-neg-slot.bin", hib_rec([hib_group(a=-1)]))
    w("spill", "seed-hib-spilled-gen.bin",
      hib_rec([hib_group(kind=1, a=0, b=5)]))  # kind 1 must carry b=0
    w("spill", "seed-hib-reserved-set.bin",
      hib_rec([hib_group()], reserved=1))
    w("spill", "seed-prefix-valid.bin", prefix_file([
        prefix_rec(2, 1, 2, 4),                        # root page
        prefix_rec(2, 1, 2, 4, parent=0, toks=[9, 10], val=2.0),
    ]))
    w("spill", "seed-prefix-empty.bin", prefix_file([]))
    w("spill", "seed-prefix-trunc.bin",
      prefix_file([prefix_rec(2, 1, 2, 4)])[:-3])
    w("spill", "seed-prefix-bad-magic.bin",
      prefix_file([prefix_rec(2, 1, 2, 4)], magic=0x58465051))
    w("spill", "seed-prefix-bad-version.bin",
      prefix_file([prefix_rec(2, 1, 2, 4)], version=9))
    w("spill", "seed-prefix-huge-count.bin",
      prefix_file([prefix_rec(2, 1, 2, 4)], count=0xFFFFFFFF))
    w("spill", "seed-prefix-forward-parent.bin",
      prefix_file([prefix_rec(2, 1, 2, 4, parent=1)]))  # self/forward
    w("spill", "seed-prefix-bit-flip.bin",
      prefix_file([prefix_rec(2, 1, 2, 4, checksum=0xDEAD)]))
    w("spill", "seed-prefix-ntoks-lies.bin",
      prefix_file([prefix_rec(2, 1, 2, 4, ntoks=3)]))

    print("gen_seeds: corpora written under", os.path.join(HERE, "corpus"))
    return 0


if __name__ == "__main__":
    sys.exit(main())
