// Fuzz target: the PS data-plane frame parser — PsServer::OnFrame in
// csrc/ptpu_ps_server.cc (v1 + traced-v2 PULL/PUSH layouts, table
// lookup, id bounds, reply sizing) down through the table gather and
// the coalescing push in csrc/ptpu_ps_table.cc. Frames are the bytes
// any authenticated client sends; every offset in them is
// attacker-controlled.
//
// Harness shape: the single-TU include idiom of
// csrc/ptpu_ps_selftest.cc reaches the anonymous-namespace PsServer
// directly; a Detached net::Conn (csrc/ptpu_net.h fuzz hook) stands
// in for a live connection, so one exec == one frame dispatch with no
// sockets in the loop. The input IS the frame payload (no u32 length
// prefix — the net core validates that before handlers run).
//
// Corpus: csrc/fuzz/corpus/wire_ps. Build: `make fuzz`.
#include "../ptpu_ps_table.cc"
#include "../ptpu_invar.cc"
#include "../ptpu_ps_server.cc"
#include "../ptpu_net.cc"
#include "../ptpu_trace.cc"

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

PsServer* g_srv = nullptr;
void* g_tab = nullptr;
void* g_tab2 = nullptr;

void InitOnce() {
  if (g_srv) return;
  g_srv = new PsServer();
  // two live shards: a plain SGD table at lo=0 and an adam table at a
  // nonzero lo (global-id offset arithmetic is part of the parser's
  // bounds story). Sizes stay tiny so pushes/pulls run in microseconds.
  g_tab = ptpu_ps_table_create(64, 4, PTPU_PS_SGD, 0.1f, 0.9f, 0.999f,
                               1e-8f);
  g_tab2 = ptpu_ps_table_create(32, 3, PTPU_PS_ADAM, 0.1f, 0.9f,
                                0.999f, 1e-8f);
  ptpu_ps_server_register(g_srv, "t", g_tab, 0);
  ptpu_ps_server_register(g_srv, "emb", g_tab2, 1000);
}

}  // namespace

extern "C" int LLVMFuzzerTestOneInput(const uint8_t* data, size_t size) {
  if (size > (1u << 20)) return 0;
  InitOnce();
  // Replay at every misalignment 0..7 (ISSUE 17): handlers parse
  // payloads in place in the reassembly buffer, where a frame lands
  // at whatever offset the preceding stream left — the unaligned-safe
  // codecs must hold (under ASan/UBSan) at every shift.
  std::vector<uint8_t> shifted(size + 8);
  for (size_t s = 0; s < 8; ++s) {
    if (size) std::memcpy(shifted.data() + s, data, size);
    auto conn = ptpu::net::Conn::Detached();
    (void)g_srv->OnFrame(conn, shifted.data() + s, uint32_t(size));
  }
  return 0;
}
