// Fuzz target: the restricted JSON walker behind GET /metrics —
// ptpu::trace::PromFromStatsJson parses the stats_json snapshot (an
// attacker cannot reach it with arbitrary bytes over the wire, but
// the walker also renders /statsz-shaped JSON handed in by tools and
// tests, and a memory-safety bug here is a memory-safety bug in every
// telemetry scrape). Also walks TracezJson's own renderer once per
// input via the query-parameter parser path in fuzz_http.cc — this
// target is the pure parser.
//
// Corpus: csrc/fuzz/corpus/json (real stats_json snapshots from both
// servers + histogram/edge shapes + invariant reports). Build:
// `make fuzz` (csrc/Makefile).
#include "../ptpu_trace.cc"
#include "../ptpu_invar.cc"

#include <cstdint>
#include <string>

extern "C" int LLVMFuzzerTestOneInput(const uint8_t* data, size_t size) {
  if (size > (1u << 20)) return 0;
  const std::string snapshot(reinterpret_cast<const char*>(data), size);
  // both family prefixes the servers use, plus an empty one
  (void)ptpu::trace::PromFromStatsJson(snapshot, "ptpu_ps");
  (void)ptpu::trace::PromFromStatsJson(snapshot, "");
  // the invariant engine walks the same restricted grammar twice over:
  // once evaluating the fuzzed snapshot against the manifest, once
  // re-parsing its OWN report (ViolationCount) — the report format is
  // deliberately inside the rj:: grammar, so this closes the loop
  (void)ptpu::invar::ViolationCount(
      ptpu::invar::CheckJson(snapshot, "serving"));
  (void)ptpu::invar::ViolationCount(
      ptpu::invar::CheckJson(snapshot, ""));
  return 0;
}
