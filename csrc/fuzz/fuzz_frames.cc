// Fuzz target: the net core's connection byte-stream state machine —
// frame reassembly (u32-LE length prefix, oversize cut, the
// exactly-32-byte pre-auth rule), the HMAC-SHA256 nonce handshake,
// and post-auth frame dispatch — driven END TO END through a real
// epoll server on loopback. This is the only harness that exercises
// the real partial-read/partial-frame paths with attacker bytes.
//
// One exec == one TCP connection: read the nonce, then
//   input[0] odd  -> answer the REAL HMAC first (covers the post-auth
//                    parser with the remaining bytes),
//   input[0] even -> raw pre-auth bytes (covers handshake rejection).
// The remaining input streams in two writes (split point derived from
// the input) to hit reassembly seams. SO_LINGER{1,0} closes with RST
// so ephemeral ports never pile up in TIME_WAIT at fuzz rates. A
// crash on an event thread takes the process down under ASan/UBSan
// with the driver's crash-dump hook holding the input.
//
// Corpus: csrc/fuzz/corpus/frames. Build: `make fuzz`.
#include "../ptpu_net.cc"
#include "../ptpu_trace.cc"

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdint>
#include <cstring>

namespace {

constexpr const char* kKey = "fuzzkey";

ptpu::net::Stats* g_stats = nullptr;
ptpu::net::Server* g_srv = nullptr;
int g_port = 0;

void InitOnce() {
  if (g_srv) return;
  g_stats = new ptpu::net::Stats();
  ptpu::net::Options opt;
  opt.authkey = kKey;
  opt.event_threads = 2;
  opt.handshake_timeout_us = 60ll * 1000 * 1000;  // fuzz decides pace
  opt.max_frame = 1u << 20;
  opt.http_port = 0;  // second protocol on the same loops
  ptpu::net::Callbacks cbs;
  cbs.on_frame = [](const ptpu::net::ConnPtr& c, const uint8_t* p,
                    uint32_t n) {
    // echo; 'X' closes; 'R' defers once (the kDefer retry path)
    if (n > 0 && p[0] == 'X') return ptpu::net::FrameResult::kClose;
    if (n > 0 && p[0] == 'R' && c->deferred_us() == 0)
      return ptpu::net::FrameResult::kDefer;
    return c->SendCopy(p, n) ? ptpu::net::FrameResult::kOk
                             : ptpu::net::FrameResult::kClose;
  };
  cbs.on_http = [](const std::string& target) {
    return ptpu::net::TelemetryHttp(
        target, [] { return std::string("{\"server\":{\"x\":1}}"); },
        "ptpu_fuzz", false);
  };
  g_srv = new ptpu::net::Server(opt, std::move(cbs), g_stats);
  std::string err;
  if (!g_srv->Start(&err)) {
    std::fprintf(stderr, "fuzz_frames: start failed: %s\n",
                 err.c_str());
    std::abort();
  }
  g_port = g_srv->port();
}

int Dial(int port) {
  for (int tries = 0; tries < 1000; ++tries) {
    const int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    sockaddr_in a{};
    a.sin_family = AF_INET;
    a.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
    a.sin_port = htons(uint16_t(port));
    if (::connect(fd, reinterpret_cast<sockaddr*>(&a), sizeof(a)) ==
        0) {
      const int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      return fd;
    }
    ::close(fd);
    // transient EADDRNOTAVAIL/ECONNREFUSED under churn: brief backoff
    ::usleep(1000);
  }
  return -1;
}

bool ReadN(int fd, uint8_t* p, size_t n) {
  size_t got = 0;
  while (got < n) {
    const ssize_t r = ::read(fd, p + got, n - got);
    if (r <= 0) return false;
    got += size_t(r);
  }
  return true;
}

void WriteAll(int fd, const uint8_t* p, size_t n) {
  size_t off = 0;
  while (off < n) {
    const ssize_t w = ::write(fd, p + off, n - off);
    if (w <= 0) return;  // peer (server) cut us: expected often
    off += size_t(w);
  }
}

void RstClose(int fd) {
  linger lg{1, 0};
  ::setsockopt(fd, SOL_SOCKET, SO_LINGER, &lg, sizeof(lg));
  ::close(fd);
}

}  // namespace

extern "C" int LLVMFuzzerTestOneInput(const uint8_t* data, size_t size) {
  if (size > (256u << 10)) return 0;
  InitOnce();
  const int fd = Dial(g_port);
  if (fd < 0) return 0;
  uint8_t nonce[16];
  if (!ReadN(fd, nonce, sizeof(nonce))) {
    RstClose(fd);
    return 0;
  }
  const uint8_t* body = data;
  size_t body_n = size;
  if (size > 0 && (data[0] & 1)) {
    // authenticate for real, then fuzz the POST-auth frame parser
    uint8_t frame[4 + 32];
    ptpu::PutU32(frame, 32);
    ptpu::HmacSha256(reinterpret_cast<const uint8_t*>(kKey),
                     std::strlen(kKey), nonce, sizeof(nonce),
                     frame + 4);
    WriteAll(fd, frame, sizeof(frame));
    uint8_t ack = 0;
    if (!ReadN(fd, &ack, 1) || ack != 0x01) {
      RstClose(fd);
      return 0;
    }
    ++body;
    --body_n;
  }
  // stream in two chunks to land on reassembly seams
  const size_t cut = body_n ? (body[0] * 131 % (body_n + 1)) : 0;
  WriteAll(fd, body, cut);
  WriteAll(fd, body + cut, body_n - cut);
  // drain whatever the echo produced without blocking forever
  timeval tv{0, 20000};
  ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  uint8_t sink[4096];
  while (::read(fd, sink, sizeof(sink)) > 0) {
  }
  RstClose(fd);
  return 0;
}
