// Fuzz target: the tuning-cache parser — ptpu::tune::ParseCacheBytes
// in csrc/ptpu_tune.h (header + record array, ISSUE 16). The cache
// file is UNTRUSTED DISK INPUT: any process that can write the cache
// path (or a stale copy from another machine) feeds these bytes to
// every predictor load, so the parser gets the same r11 treatment as
// wire frames — bounds-checked, fuzzed, and every malformed shape
// degrades to "adopt nothing, re-probe silently", never a crash.
//
// Harness shape: bytes in, ParseCacheBytes against both the matching
// and a mismatching cpu signature (the first 8 input bytes double as
// the expected signature so the fuzzer can reach kOk and kWrongCpu
// with the same mutation budget). Well-formed inputs additionally
// round-trip through SerializeCache and must re-parse identically —
// canonicalization bugs surface as an abort here, not as a silently
// rewritten cache in production. The Registry singleton's merge path
// (validity re-check + first-insert-wins) runs on every parsed entry
// set via a memory-only exercise of Insert/Lookup.
//
// Corpus: csrc/fuzz/corpus/tune (valid caches, truncations, huge
// counts, wrong cpuid, overflowing offsets — csrc/fuzz/gen_seeds.py).
// Build: `make fuzz`.
#include "../ptpu_tune.cc"

#include <cassert>
#include <cstdint>
#include <vector>

extern "C" int LLVMFuzzerTestOneInput(const uint8_t* data, size_t size) {
  if (size > (1u << 20)) return 0;
  namespace tn = ptpu::tune;
  // derive the "expected" signature from the input so mutated headers
  // can hit every ParseResult without knowing this machine's CpuSig
  uint64_t sig = 0;
  if (size >= tn::kTuneHeaderBytes) std::memcpy(&sig, data + 8, 8);
  std::vector<std::pair<tn::TuneKey, tn::TuneConfig>> out, scratch;
  const tn::ParseResult r = tn::ParseCacheBytes(data, size, sig, &out);
  // flipped signature: same bytes must land in kWrongCpu, not adopt
  (void)tn::ParseCacheBytes(data, size, sig ^ 0x517cc1b727220a95ull,
                            &scratch);
  if (r == tn::ParseResult::kOk) {
    // canonical round trip: serialize the adopted entries and re-parse
    std::vector<uint8_t> bytes;
    tn::SerializeCache(out, sig, &bytes);
    std::vector<std::pair<tn::TuneKey, tn::TuneConfig>> again;
    const tn::ParseResult r2 =
        tn::ParseCacheBytes(bytes.data(), bytes.size(), sig, &again);
    assert(r2 == tn::ParseResult::kOk);
    assert(again.size() == out.size());
    for (size_t i = 0; i < out.size(); ++i) {
      assert(again[i].first.m == out[i].first.m &&
             again[i].first.n == out[i].first.n &&
             again[i].first.k == out[i].first.k &&
             again[i].first.dtype == out[i].first.dtype);
      assert(again[i].second == out[i].second);
    }
    // registry merge path: every adopted entry must survive the
    // Insert validity re-check and come back from Lookup
    auto& reg = tn::Registry::Inst();
    for (const auto& e : out) reg.Insert(e.first, e.second);
    tn::TuneConfig got;
    for (const auto& e : out) assert(reg.Lookup(e.first, &got));
    reg.Clear();
  }
  return 0;
}
