// Fuzz target: the ONNX artifact loader in csrc/ptpu_predictor.cc —
// the protobuf wire Reader, parse_model / parse_tensor / parse_attr /
// parse_value_info, and (for inputs that survive parsing) the FULL
// predictor load pipeline: shape inference, load-time fusion passes,
// the static memory planner's dry run. Artifacts come from disk and
// are the deployment trust boundary (PAPER.md: a serving process
// loads artifacts produced elsewhere).
//
// Two layers per input:
//   1. parse_model on the raw bytes (cheap, throws on malformed);
//   2. when layer 1 yields any node, the bytes are replayed through
//      ptpu_predictor_create via memfd (/proc/self/fd) so the
//      planner/fusion layers see them too.
//
// Corpus: csrc/fuzz/corpus/onnx (real selftest artifacts, an all-ops
// graph, truncations). Build: `make fuzz` (csrc/Makefile).
#include "../ptpu_predictor.cc"

#include <sys/mman.h>
#include <unistd.h>

#include <cstdint>
#include <cstdio>
#include <string>

extern "C" int LLVMFuzzerTestOneInput(const uint8_t* data, size_t size) {
  if (size > (1u << 20)) return 0;
  const std::string bytes(reinterpret_cast<const char*>(data), size);
  bool have_nodes = false;
  try {
    Graph g = parse_model(bytes);
    have_nodes = !g.nodes.empty();
  } catch (const std::exception&) {
    // malformed-model rejection IS the contract
  }
  if (!have_nodes) return 0;
  const int fd = ::memfd_create("fuzz_onnx", 0);
  if (fd < 0) return 0;
  if (::write(fd, bytes.data(), bytes.size()) ==
      ssize_t(bytes.size())) {
    char path[64];
    std::snprintf(path, sizeof(path), "/proc/self/fd/%d", fd);
    char err[256];
    PTPU_Predictor* p = ptpu_predictor_create(path, err, sizeof(err));
    if (p) ptpu_predictor_destroy(p);
  }
  ::close(fd);
  return 0;
}
