// Native unit tests for the PS shard table + data-plane server TUs —
// the cc_test analogue (same harness idiom as ptpu_selftest.cc: plain
// asserts, exit 0 = pass; wrapped by tests/test_native_selftest.py via
// `make selftest`).
#include "ptpu_net.cc"
#include "ptpu_trace.cc"
#include "ptpu_invar.cc"
#include "ptpu_ps_server.cc"
#include "ptpu_ps_table.cc"

// asserts ARE the test — never compile them out, even under a
// release-style CXXFLAGS override carrying -DNDEBUG
#undef NDEBUG
#include <atomic>
#include <cassert>
#include <chrono>
#include <cstdio>
#include <random>
#include <string>
#include <thread>

// the handshake/exact-IO helpers live in the shared headers now (the
// server TU no longer re-exports them into its anonymous namespace)
using ptpu::HmacSha256;
using ptpu::ReadExact;
using ptpu::Sha256;
using ptpu::WriteExact;

namespace {

constexpr float kTol = 1e-5f;

bool close(float a, float b, float tol = kTol) {
  return std::fabs(a - b) <= tol * (1.f + std::fabs(b));
}

void fill_random(void *h, std::mt19937 &rng) {
  auto *t = static_cast<PsTable *>(h);
  std::uniform_real_distribution<float> d(-1.f, 1.f);
  for (int64_t i = 0; i < t->rows * t->dim; ++i) t->w[i] = d(rng);
}

void test_pull_gathers_rows() {
  void *h = ptpu_ps_table_create(8, 3, PTPU_PS_SGD, 0.1f, 0, 0, 0);
  assert(h);
  float *w = ptpu_ps_table_data(h);
  for (int64_t i = 0; i < 8 * 3; ++i) w[i] = float(i);
  const int64_t ids[4] = {7, 0, 3, 7};
  float out[12];
  assert(ptpu_ps_table_pull(h, ids, 4, out) == 0);
  for (int64_t i = 0; i < 4; ++i)
    for (int64_t d = 0; d < 3; ++d)
      assert(out[i * 3 + d] == float(ids[i] * 3 + d));
  ptpu_ps_table_destroy(h);
}

void test_pull_bounds_checked() {
  void *h = ptpu_ps_table_create(4, 2, PTPU_PS_SGD, 0.1f, 0, 0, 0);
  const int64_t bad_hi[1] = {4}, bad_lo[1] = {-1};
  float out[2];
  assert(ptpu_ps_table_pull(h, bad_hi, 1, out) == -1);
  assert(std::string(ptpu_ps_last_error()).find("out of range") !=
         std::string::npos);
  assert(ptpu_ps_table_pull(h, bad_lo, 1, out) == -1);
  assert(ptpu_ps_table_push(h, bad_hi, 1, out) == -1);
  ptpu_ps_table_destroy(h);
}

void test_push_sgd_coalesces_duplicates() {
  void *h = ptpu_ps_table_create(6, 2, PTPU_PS_SGD, 0.5f, 0, 0, 0);
  auto *t = static_cast<PsTable *>(h);
  for (int64_t i = 0; i < 12; ++i) t->w[i] = 1.f;
  // row 2 hit twice: grads accumulate BEFORE the single update
  const int64_t ids[3] = {2, 4, 2};
  const float g[6] = {1.f, 0.f, 3.f, 3.f, 0.5f, 0.5f};
  assert(ptpu_ps_table_push(h, ids, 3, g) == 0);
  assert(close(t->w[2 * 2 + 0], 1.f - 0.5f * 1.5f));
  assert(close(t->w[2 * 2 + 1], 1.f - 0.5f * 0.5f));
  assert(close(t->w[4 * 2 + 0], 1.f - 0.5f * 3.f));
  assert(close(t->w[4 * 2 + 1], 1.f - 0.5f * 3.f));
  assert(close(t->w[0], 1.f));  // untouched row
  ptpu_ps_table_destroy(h);
}

void test_push_adagrad_matches_reference() {
  const float lr = 0.3f, eps = 1e-8f;
  void *h = ptpu_ps_table_create(4, 2, PTPU_PS_ADAGRAD, lr, 0, 0, eps);
  auto *t = static_cast<PsTable *>(h);
  std::mt19937 rng(7);
  fill_random(h, rng);
  float w0[2] = {t->w[2], t->w[3]};  // row 1
  float g2ref[2] = {0.f, 0.f};
  const int64_t ids[1] = {1};
  for (int step = 0; step < 3; ++step) {
    const float g[2] = {0.5f + step, -0.25f};
    assert(ptpu_ps_table_push(h, ids, 1, g) == 0);
    for (int d = 0; d < 2; ++d) {
      g2ref[d] += g[d] * g[d];
      w0[d] -= lr * g[d] / (std::sqrt(g2ref[d]) + eps);
    }
  }
  assert(close(t->w[2], w0[0]) && close(t->w[3], w0[1]));
  ptpu_ps_table_destroy(h);
}

void test_push_adam_per_row_step() {
  const float lr = 0.1f, b1 = 0.9f, b2 = 0.999f, eps = 1e-8f;
  void *h = ptpu_ps_table_create(4, 1, PTPU_PS_ADAM, lr, b1, b2, eps);
  auto *t = static_cast<PsTable *>(h);
  t->w[0] = 1.f;
  t->w[2] = 1.f;
  // row 0 updated twice, row 2 once — row 2's bias correction must use
  // ITS step count (1), not a global one
  const int64_t id0[1] = {0}, id2[1] = {2};
  const float g[1] = {0.5f};
  float m = 0.f, v = 0.f, w = 1.f;
  for (int step = 1; step <= 2; ++step) {
    assert(ptpu_ps_table_push(h, id0, 1, g) == 0);
    m = b1 * m + (1 - b1) * g[0];
    v = b2 * v + (1 - b2) * g[0] * g[0];
    const float mhat = m / (1 - std::pow(b1, float(step)));
    const float vhat = v / (1 - std::pow(b2, float(step)));
    w -= lr * mhat / (std::sqrt(vhat) + eps);
  }
  assert(close(t->w[0], w));
  assert(ptpu_ps_table_push(h, id2, 1, g) == 0);
  const float mhat1 = ((1 - b1) * g[0]) / (1 - b1);
  const float vhat1 = ((1 - b2) * g[0] * g[0]) / (1 - b2);
  assert(close(t->w[2], 1.f - lr * mhat1 / (std::sqrt(vhat1) + eps)));
  assert(t->steps[0] == 2 && t->steps[2] == 1 && t->steps[1] == 0);
  ptpu_ps_table_destroy(h);
}

void test_arena_layout_disjoint() {
  // PlanArena must hand out non-overlapping, aligned regions inside
  // the one block
  void *h = ptpu_ps_table_create(16, 8, PTPU_PS_ADAM, 0.1f, 0.9f,
                                 0.999f, 1e-8f);
  auto *t = static_cast<PsTable *>(h);
  const size_t wn = 16 * 8 * sizeof(float);
  auto b = [&](void *p) { return reinterpret_cast<char *>(p); };
  assert(b(t->w) >= t->base && b(t->w) + wn <= t->base + t->bytes);
  assert(b(t->slot0) >= b(t->w) + wn || b(t->w) >= b(t->slot0) + wn);
  assert(b(t->slot1) >= t->base && b(t->slot1) + wn <= t->base + t->bytes);
  assert(reinterpret_cast<uintptr_t>(t->w) % 64 == 0 ||
         reinterpret_cast<uintptr_t>(t->base) % 64 != 0);
  ptpu_ps_table_destroy(h);
}

void test_concurrent_pulls_and_push() {
  // shared-lock pulls racing an exclusive-lock push: every pulled row
  // must be either the before or the after value, never a torn mix
  const int64_t rows = 64, dim = 16;
  void *h = ptpu_ps_table_create(rows, dim, PTPU_PS_SGD, 1.f, 0, 0, 0);
  auto *t = static_cast<PsTable *>(h);
  for (int64_t i = 0; i < rows * dim; ++i) t->w[i] = 1.f;
  std::vector<int64_t> all(rows);
  for (int64_t i = 0; i < rows; ++i) all[i] = i;
  std::vector<float> ones(size_t(rows) * dim, 1.f);

  std::atomic<bool> bad{false};
  auto puller = [&]() {
    std::vector<float> out(size_t(rows) * dim);
    for (int it = 0; it < 200; ++it) {
      if (ptpu_ps_table_pull(h, all.data(), rows, out.data()) != 0) {
        bad = true;
        return;
      }
      for (int64_t r = 0; r < rows; ++r) {
        const float first = out[r * dim];
        for (int64_t d = 1; d < dim; ++d)
          if (out[r * dim + d] != first) {  // torn row
            bad = true;
            return;
          }
      }
    }
  };
  std::thread p1(puller), p2(puller);
  for (int it = 0; it < 200; ++it)
    assert(ptpu_ps_table_push(h, all.data(), rows, ones.data()) == 0);
  p1.join();
  p2.join();
  assert(!bad.load());
  // 200 pushes of grad 1 at lr 1: every weight is 1 - 200
  for (int64_t i = 0; i < rows * dim; ++i) assert(t->w[i] == -199.f);
  ptpu_ps_table_destroy(h);
}

void test_create_rejects_bad_args() {
  assert(ptpu_ps_table_create(0, 4, PTPU_PS_SGD, 0.1f, 0, 0, 0) ==
         nullptr);
  assert(ptpu_ps_table_create(4, 4, 99, 0.1f, 0, 0, 0) == nullptr);
}

bool json_has(const std::string &json, const std::string &frag) {
  return json.find(frag) != std::string::npos;
}

void test_table_stats_counters() {
  void *h = ptpu_ps_table_create(8, 2, PTPU_PS_SGD, 0.1f, 0, 0, 0);
  const int64_t ids[3] = {1, 5, 1};  // one duplicate
  float out[6];
  const float g[6] = {0, 0, 0, 0, 0, 0};
  assert(ptpu_ps_table_pull(h, ids, 3, out) == 0);
  assert(ptpu_ps_table_pull(h, ids, 2, out) == 0);
  assert(ptpu_ps_table_push(h, ids, 3, g) == 0);
  ptpu_ps_table_note_pull(h, 7);  // external-gather credit path
  std::string j = ptpu_ps_table_stats_json(h);
  assert(json_has(j, "\"pull_ops\":3"));
  assert(json_has(j, "\"pull_rows\":12"));  // 3 + 2 + 7
  assert(json_has(j, "\"push_ops\":1"));
  assert(json_has(j, "\"push_rows\":3"));
  // 3 pushed rows collapsed to 2 unique -> 1 coalesced
  assert(json_has(j, "\"push_coalesced_rows\":1"));
  // a failed pull (out-of-range id) must not count
  const int64_t bad[1] = {99};
  assert(ptpu_ps_table_pull(h, bad, 1, out) == -1);
  j = ptpu_ps_table_stats_json(h);
  assert(json_has(j, "\"pull_ops\":3"));
  ptpu_ps_table_stats_reset(h);
  j = ptpu_ps_table_stats_json(h);
  assert(json_has(j, "\"pull_ops\":0"));
  assert(json_has(j, "\"push_coalesced_rows\":0"));
  ptpu_ps_table_destroy(h);
}

void test_stats_hist_buckets() {
  // log2 bucket layout shared with paddle_tpu/profiler/stats.py —
  // boundaries must match exactly or native/python merges skew
  assert(ptpu::HistBucketOf(0) == 0);
  assert(ptpu::HistBucketOf(1) == 1);
  assert(ptpu::HistBucketOf(2) == 2);
  assert(ptpu::HistBucketOf(3) == 2);
  assert(ptpu::HistBucketOf(4) == 3);
  assert(ptpu::HistBucketOf(1023) == 10);
  assert(ptpu::HistBucketOf(1024) == 11);
  assert(ptpu::HistBucketOf(~0ull) == ptpu::kHistBuckets - 1);
  ptpu::Histogram hst;
  hst.Observe(0);
  hst.Observe(3);
  hst.Observe(3);
  assert(hst.count.load() == 3 && hst.sum.load() == 6);
  assert(hst.buckets[0].load() == 1 && hst.buckets[2].load() == 2);
  // relaxed counters still sum exactly under contention
  ptpu::Counter c;
  std::thread a([&] { for (int i = 0; i < 50000; ++i) c.Add(1); });
  std::thread b([&] { for (int i = 0; i < 50000; ++i) c.Add(2); });
  a.join();
  b.join();
  assert(c.Get() == 150000);
}

// ---- data-plane server (ptpu_ps_server.cc) ------------------------------

void test_sha256_known_vector() {
  // FIPS 180-2 "abc"
  Sha256 s;
  s.Update(reinterpret_cast<const uint8_t *>("abc"), 3);
  uint8_t out[32];
  s.Final(out);
  const uint8_t want[32] = {
      0xba, 0x78, 0x16, 0xbf, 0x8f, 0x01, 0xcf, 0xea, 0x41, 0x41, 0x40,
      0xde, 0x5d, 0xae, 0x22, 0x23, 0xb0, 0x03, 0x61, 0xa3, 0x96, 0x17,
      0x7a, 0x9c, 0xb4, 0x10, 0xff, 0x61, 0xf2, 0x00, 0x15, 0xad};
  assert(std::memcmp(out, want, 32) == 0);
  // RFC 4231 test case 2: HMAC-SHA256("Jefe", "what do ya want ...")
  uint8_t mac[32];
  const char *key = "Jefe";
  const char *msg = "what do ya want for nothing?";
  HmacSha256(reinterpret_cast<const uint8_t *>(key), 4,
             reinterpret_cast<const uint8_t *>(msg), std::strlen(msg),
             mac);
  const uint8_t want2[8] = {0x5b, 0xdc, 0xc1, 0x46,
                            0xbf, 0x60, 0x75, 0x4e};
  assert(std::memcmp(mac, want2, 8) == 0);
}

int dial(int port) {
  const int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  assert(fd >= 0);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons(uint16_t(port));
  assert(::connect(fd, reinterpret_cast<sockaddr *>(&addr),
                   sizeof(addr)) == 0);
  return fd;
}

bool client_handshake(int fd, const std::string &key) {
  uint8_t nonce[16];
  if (!ReadExact(fd, nonce, 16)) return false;
  uint8_t mac[32];
  HmacSha256(reinterpret_cast<const uint8_t *>(key.data()), key.size(),
             nonce, 16, mac);
  const uint8_t lenb[4] = {32, 0, 0, 0};
  if (!WriteExact(fd, lenb, 4) || !WriteExact(fd, mac, 32)) return false;
  uint8_t ok = 0;
  return ReadExact(fd, &ok, 1) && ok == 0x01;
}

void send_client_frame(int fd, const std::vector<uint8_t> &payload) {
  const uint32_t n = uint32_t(payload.size());
  const uint8_t lenb[4] = {uint8_t(n), uint8_t(n >> 8), uint8_t(n >> 16),
                           uint8_t(n >> 24)};
  assert(WriteExact(fd, lenb, 4));
  assert(WriteExact(fd, payload.data(), n));
}

std::vector<uint8_t> recv_client_frame(int fd) {
  uint8_t lenb[4];
  assert(ReadExact(fd, lenb, 4));
  const uint32_t n = uint32_t(lenb[0]) | uint32_t(lenb[1]) << 8 |
                     uint32_t(lenb[2]) << 16 | uint32_t(lenb[3]) << 24;
  std::vector<uint8_t> out(n);
  assert(ReadExact(fd, out.data(), n));
  return out;
}

void test_server_pull_push_roundtrip() {
  void *t = ptpu_ps_table_create(8, 2, PTPU_PS_SGD, 1.f, 0, 0, 0);
  auto *pt = static_cast<PsTable *>(t);
  for (int64_t i = 0; i < 16; ++i) pt->w[i] = float(i);
  void *srv = ptpu_ps_server_start(0, "k3y", 3, /*loopback_only=*/1);
  assert(srv);
  // shard offset lo=100: the server must translate global->local ids
  assert(ptpu_ps_server_register(srv, "emb", t, 100) == 0);
  const int port = ptpu_ps_server_port(srv);
  assert(port > 0);

  const int fd = dial(port);
  assert(client_handshake(fd, "k3y"));

  // PULL_REQ for global ids {103, 100}
  std::vector<uint8_t> req = {1, 0x50, 3, 'e', 'm', 'b', 2, 0, 0, 0};
  const int64_t gids[2] = {103, 100};
  const auto *gb = reinterpret_cast<const uint8_t *>(gids);
  req.insert(req.end(), gb, gb + 16);
  send_client_frame(fd, req);
  auto rep = recv_client_frame(fd);
  assert(rep.size() == 10 + 2 * 2 * 4 && rep[1] == 0x51);
  // the f32 body starts at +10 (odd alignment): unaligned-safe reads
  const auto row_at = [&](size_t k) {
    return ptpu::GetF32(rep.data() + 10 + 4 * k);
  };
  assert(row_at(0) == 6.f && row_at(1) == 7.f);  // row 3
  assert(row_at(2) == 0.f && row_at(3) == 1.f);  // row 0

  // PUSH_REQ: grad 1 to global id 103 twice (coalesced, lr=1)
  std::vector<uint8_t> push = {1, 0x52, 3, 'e', 'm', 'b',
                               0,                 // flags
                               2, 0, 0, 0,        // n
                               2, 0, 0, 0};       // dim
  push.insert(push.end(), gb, gb + 8);            // id 103
  push.insert(push.end(), gb, gb + 8);            // id 103 again
  const float g[4] = {1.f, 0.5f, 2.f, 0.25f};
  const auto *gp = reinterpret_cast<const uint8_t *>(g);
  push.insert(push.end(), gp, gp + 16);
  send_client_frame(fd, push);
  auto ok = recv_client_frame(fd);
  assert(ok.size() == 2 && ok[1] == 0x53);
  assert(pt->w[6] == 6.f - 3.f && pt->w[7] == 7.f - 0.75f);

  // unknown table -> ERR frame, connection stays usable
  std::vector<uint8_t> bad = {1, 0x50, 2, 'n', 'o', 1, 0, 0, 0};
  bad.insert(bad.end(), gb, gb + 8);
  send_client_frame(fd, bad);
  auto err = recv_client_frame(fd);
  assert(err.size() >= 2 && err[1] == 0x54);
  send_client_frame(fd, req);
  assert(recv_client_frame(fd)[1] == 0x51);

  // wire stats saw 2 successful pulls (4 rows), 1 push (2 rows), the
  // unknown-table ERR frame, and credited the table's storage view.
  // Counters land AFTER the reply write, so the serve thread may trail
  // the client's recv by an instant — poll briefly.
  std::string sj, global;
  for (int spin = 0; spin < 200; ++spin) {
    sj = ptpu_ps_server_stats_json(srv);
    // the GLOBAL wire counters only — the per-table sections repeat
    // the same key names, so asserting on the whole JSON would let a
    // dead global counter hide behind a live per-table one
    global = sj.substr(0, sj.find("\"tables\""));
    if (json_has(global, "\"pull_ops\":2")) break;
    std::this_thread::sleep_for(std::chrono::milliseconds(5));
  }
  assert(json_has(global, "\"pull_ops\":2"));
  assert(json_has(global, "\"pull_rows\":4"));
  assert(json_has(global, "\"push_ops\":1"));
  assert(json_has(global, "\"push_rows\":2"));
  assert(json_has(global, "\"err_frames\":1"));
  assert(json_has(sj, "\"emb\""));
  assert(!json_has(global, "\"count\":0,\"sum\":0"));  // latency seen
  const std::string tj = ptpu_ps_table_stats_json(t);
  assert(json_has(tj, "\"pull_ops\":2") && json_has(tj, "\"pull_rows\":4"));
  ptpu_ps_server_stats_reset(srv);
  const std::string rj = ptpu_ps_server_stats_json(srv);
  assert(json_has(rj, "\"pull_ops\":0"));
  assert(json_has(std::string(ptpu_ps_table_stats_json(t)),
                  "\"pull_ops\":0"));

  ::close(fd);
  // bad authkey must be rejected
  const int fd2 = dial(port);
  assert(!client_handshake(fd2, "wrong"));
  ::close(fd2);

  ptpu_ps_server_stop(srv);
  ptpu_ps_table_destroy(t);
}

/* ISSUE 20: the conservation-law gate on the PS plane. A quiesced
 * PS snapshot (including a failed handshake and a stats_reset racing
 * an open conn) passes every manifest law; a doctored snapshot (a
 * conn accepted but never closed — e.g. a lost FinishClose bump)
 * trips conn_balance; plane sniffing resolves a batcher-less
 * snapshot to "ps". */
void test_invar_ps_gate() {
  void *t = ptpu_ps_table_create(8, 2, PTPU_PS_SGD, 1.f, 0, 0, 0);
  void *srv = ptpu_ps_server_start(0, "k3y", 3, /*loopback_only=*/1);
  assert(srv && ptpu_ps_server_register(srv, "emb", t, 0) == 0);
  const int port = ptpu_ps_server_port(srv);

  const int fd = dial(port);
  assert(client_handshake(fd, "k3y"));
  // reset while this conn is open: the conn-ledger rebase must keep
  // conn_balance exact (accepted rebases by the CLOSED base only)
  ptpu_ps_server_stats_reset(srv);
  std::vector<uint8_t> req = {1, 0x50, 3, 'e', 'm', 'b', 1, 0, 0, 0};
  const int64_t gid = 3;
  const auto *gb = reinterpret_cast<const uint8_t *>(&gid);
  req.insert(req.end(), gb, gb + 8);
  send_client_frame(fd, req);
  assert(recv_client_frame(fd)[1] == 0x51);
  ::close(fd);
  const int fd2 = dial(port);
  assert(!client_handshake(fd2, "wrong"));  // handshake_fails + close
  ::close(fd2);

  // quiesce: wait out the async close bookkeeping
  std::string sj;
  for (int spin = 0; spin < 400; ++spin) {
    sj = ptpu_ps_server_stats_json(srv);
    if (sj.find("\"conns_active\":0") != std::string::npos) break;
    std::this_thread::sleep_for(std::chrono::milliseconds(5));
  }
  assert(ptpu::invar::GateQuiesced(sj, "ps", "selftest") == 0);

  // plane sniffing over the C ABI: no batcher section -> "ps"
  const std::string rep = ptpu_invar_check_json(sj.c_str(), nullptr);
  assert(rep.find("\"plane\":\"ps\"") != std::string::npos);
  assert(ptpu::invar::ViolationCount(rep) == 0);

  // doctored snapshot: a conn accepted but never closed nor active
  const size_t ap = sj.find("\"conns_accepted\":");
  assert(ap != std::string::npos);
  const uint64_t acc =
      std::strtoull(sj.c_str() + ap + 17, nullptr, 10);
  std::string bad = sj.substr(0, ap) + "\"conns_accepted\":" +
                    std::to_string(acc + 1) +
                    sj.substr(sj.find(',', ap));
  const std::string vrep = ptpu::invar::CheckJson(bad, "ps");
  assert(ptpu::invar::ViolationCount(vrep) == 1);
  assert(vrep.find("\"conn_balance\"") != std::string::npos);

  ptpu_ps_server_stop(srv);
  ptpu_ps_table_destroy(t);
  std::printf("ps invar gate: quiesce, reset, sniff, negative OK\n");
}

}  // namespace

int main() {
  // every ptpu_ps_server_stop below runs the conservation gate
  // fatally (ptpu::invar::GateQuiesced abort()s under this env)
  setenv("PTPU_INVAR_FATAL", "1", 1);
  test_pull_gathers_rows();
  test_pull_bounds_checked();
  test_push_sgd_coalesces_duplicates();
  test_push_adagrad_matches_reference();
  test_push_adam_per_row_step();
  test_arena_layout_disjoint();
  test_concurrent_pulls_and_push();
  test_create_rejects_bad_args();
  test_table_stats_counters();
  test_stats_hist_buckets();
  test_sha256_known_vector();
  test_server_pull_push_roundtrip();
  test_invar_ps_gate();
  std::printf("all native ps-table unit tests passed\n");
  return 0;
}
