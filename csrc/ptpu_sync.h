// Condition-variable timed-wait helpers shared by the native TUs
// (csrc/ptpu_serving.cc batcher, csrc/ptpu_runtime.cc blocking queue).
//
// Why this exists: libstdc++ (>= 9) lowers steady-clock
// condition_variable::wait_for / wait_until to pthread_cond_clockwait,
// which the libtsan shipped with gcc-10 does NOT intercept. An
// unintercepted wait means TSan never sees the mutex being released
// and reacquired inside the wait, its lockset goes inconsistent, and
// it then reports phantom "double lock of a mutex" plus data races on
// perfectly lock-protected state (reproduced in isolation on this
// toolchain; both sides of the reported races hold the same mutex).
//
// Under TSan we therefore wait on the SYSTEM clock, which lowers to
// the intercepted pthread_cond_timedwait. A wall-clock jump during the
// wait can lengthen/shorten the timeout — harmless for a sanitizer
// run, and every call site re-checks its predicate/deadline in a loop
// anyway (the lint in tools/ptpu_check.py enforces that). Production
// builds keep the steady clock.
#ifndef PTPU_SYNC_H_
#define PTPU_SYNC_H_

#include <chrono>
#include <condition_variable>
#include <mutex>

#if defined(__SANITIZE_THREAD__)
#define PTPU_TSAN_BUILD 1
#elif defined(__has_feature)
#if __has_feature(thread_sanitizer)
#define PTPU_TSAN_BUILD 1
#endif
#endif

namespace ptpu {

// Timed wait without predicate: the caller MUST loop on its own
// predicate/deadline around this (condvar waits wake spuriously).
inline void CvWaitForUs(std::condition_variable &cv,
                        std::unique_lock<std::mutex> &l, int64_t usec) {
#if defined(PTPU_TSAN_BUILD)
  cv.wait_until(l, std::chrono::system_clock::now() +
                       std::chrono::microseconds(usec));
#else
  cv.wait_for(l, std::chrono::microseconds(usec));
#endif
}

// Timed wait with predicate; returns the predicate's final value
// (false == timed out with the predicate still unsatisfied).
template <class Pred>
inline bool CvWaitForUs(std::condition_variable &cv,
                        std::unique_lock<std::mutex> &l, int64_t usec,
                        Pred pred) {
#if defined(PTPU_TSAN_BUILD)
  return cv.wait_until(l,
                       std::chrono::system_clock::now() +
                           std::chrono::microseconds(usec),
                       pred);
#else
  return cv.wait_for(l, std::chrono::microseconds(usec), pred);
#endif
}

}  // namespace ptpu

#endif  // PTPU_SYNC_H_
