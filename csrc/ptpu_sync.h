// ptpu_sync — the ONE synchronization layer of the native runtime.
//
// Every mutex / shared-mutex / condition-variable in csrc lives behind
// the wrappers in this header (tools/ptpu_check.py's `sync` checker
// bans the raw std:: primitives everywhere else). Two reasons:
//
//  1. ptpu_lockdep (ISSUE 11): a ranked-mutex validator in the spirit
//     of the kernel's lockdep. Every lock belongs to a named
//     LockClass with an explicit RANK (the position in the global
//     acquisition order, low acquired first — table in README
//     "Correctness tooling"). Debug builds (-DPTPU_LOCKDEP, default
//     for selftests/sancheck/`make fuzz` off, see csrc/Makefile)
//     check, on EVERY acquisition:
//       * rank order: acquiring a lock whose rank is <= the highest
//         held rank is an inversion (same class twice = recursion);
//       * the acquisition-order graph: each held->new class pair is
//         an edge; an edge that closes a cycle is an ABBA deadlock
//         that merely hasn't fired yet. Both the current acquisition
//         stack and the first-recorded stack of the conflicting edge
//         are printed;
//       * held-across-blocking: waiting on a condition variable while
//         holding any OTHER lock whose class is not kLockAllowBlock
//         (event-loop-side locks must never be held across a sleep).
//     A violation prints both stacks and abort()s (fail-fast, like
//     the sanitizers). Shipping builds compile the wrappers to
//     zero-cost pass-throughs: Mutex IS std::mutex plus nothing
//     (tests/test_lockdep.py asserts no lockdep symbol reaches a
//     shipping .so).
//
//  2. TSan-safe timed waits. libstdc++ (>= 9) lowers steady-clock
//     condition_variable::wait_for / wait_until to
//     pthread_cond_clockwait, which the libtsan shipped with gcc-10
//     does NOT intercept. An unintercepted wait means TSan never sees
//     the mutex being released and reacquired inside the wait, its
//     lockset goes inconsistent, and it then reports phantom "double
//     lock of a mutex" plus data races on perfectly lock-protected
//     state (reproduced in isolation on this toolchain). Under TSan
//     we therefore wait on the SYSTEM clock, which lowers to the
//     intercepted pthread_cond_timedwait. A wall-clock jump during
//     the wait can lengthen/shorten the timeout — harmless for a
//     sanitizer run, and every call site re-checks its
//     predicate/deadline in a loop anyway (the `locks` lint in
//     tools/ptpu_check.py enforces that). Production builds keep the
//     steady clock.
#ifndef PTPU_SYNC_H_
#define PTPU_SYNC_H_

#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <mutex>
#include <shared_mutex>

#if defined(PTPU_SCHEDCK)
// Model-checker hooks (schedck test builds only — the shipping .so
// rules refuse -DPTPU_SCHEDCK). Each On*() returns true when the
// calling thread is owned by an active schedck exploration, in which
// case the operation happened in the MODEL and the real primitive
// must not be touched; unmanaged threads fall through unchanged.
#include "ptpu_schedck.h"
#endif

#if defined(PTPU_LOCKDEP)
#include <execinfo.h>

#include <atomic>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#endif

#if defined(__SANITIZE_THREAD__)
#define PTPU_TSAN_BUILD 1
#elif defined(__has_feature)
#if __has_feature(thread_sanitizer)
#define PTPU_TSAN_BUILD 1
#endif
#endif

namespace ptpu {

// LockClass flags.
enum : unsigned {
  // This class is DESIGNED to be held across a blocking wait (e.g.
  // WorkPool's dispatch mutex serializes whole dispatches, waiting
  // out cv_done_ while held is the point; the serving kv mutex is
  // held across whole decode runs). Everything else reports when held
  // across a CondVar wait.
  kLockAllowBlock = 1u,
};

#if defined(PTPU_LOCKDEP)

namespace lockdep {

constexpr int kMaxClasses = 64;
constexpr int kMaxHeld = 16;    // deepest legal nesting per thread
constexpr int kStackDepth = 24; // frames captured per acquisition

struct ClassInfo {
  const char* name;
  int rank;
  unsigned flags;
};

struct Stack {
  void* pc[kStackDepth];
  int n = 0;
  void Capture() { n = ::backtrace(pc, kStackDepth); }
};

struct Edge {         // first-seen evidence for class pair from->to
  bool present = false;
  Stack from_stack;   // where `from` was acquired (the holder)
  Stack to_stack;     // where `to` was then acquired
};

struct HeldLock {
  int cls = -1;
  const void* addr = nullptr;
  bool shared = false;
  Stack stack;        // where this lock was acquired
};

struct State {
  std::mutex mu;  // raw on purpose: the validator must not validate
                  // itself (this header is the one exempt file)
  ClassInfo classes[kMaxClasses] = {};
  std::atomic<int> n_classes{0};
  uint64_t adj[kMaxClasses] = {};       // adjacency bitset, a->b
  Edge* edges = nullptr;                // kMaxClasses * kMaxClasses
  std::atomic<uint64_t> violations{0};  // for tests; reports abort()

  State() { edges = new Edge[kMaxClasses * kMaxClasses]; }
};

inline State& state() {
  static State s;
  return s;
}

struct ThreadHeld {
  HeldLock h[kMaxHeld];
  int n = 0;
};

inline ThreadHeld& held() {
  thread_local ThreadHeld t;
  return t;
}

inline int RegisterClass(const char* name, int rank, unsigned flags) {
  State& s = state();
  const int id = s.n_classes.fetch_add(1, std::memory_order_relaxed);
  if (id >= kMaxClasses) {
    std::fprintf(stderr,
                 "ptpu_lockdep: more than %d lock classes (registering "
                 "\"%s\") — raise kMaxClasses\n",
                 kMaxClasses, name);
    std::abort();
  }
  s.classes[id] = ClassInfo{name, rank, flags};
  return id;
}

inline void PrintStack(const char* label, const Stack& st) {
  std::fprintf(stderr, ">>> stack %s:\n", label);
  if (st.n > 0) ::backtrace_symbols_fd(st.pc, st.n, 2);
  std::fflush(stderr);
}

// One report == one abort (fail-fast like -fno-sanitize-recover);
// PTPU_LOCKDEP_NOABORT=1 downgrades to count-and-continue so a test
// can observe several reports in one process if it ever needs to.
inline void ReportEnd() {
  state().violations.fetch_add(1, std::memory_order_relaxed);
  const char* e = std::getenv("PTPU_LOCKDEP_NOABORT");
  if (e && e[0] == '1') return;
  std::abort();
}

// DFS over the class-order graph: true when `to` can already reach
// `from` (so adding from->to would close a cycle). Caller holds
// state().mu.
inline bool Reaches(const State& s, int src, int dst) {
  uint64_t visited = 0, frontier = 1ull << src;
  while (frontier) {
    if (frontier & (1ull << dst)) return true;
    visited |= frontier;
    uint64_t next = 0;
    for (int i = 0; i < kMaxClasses; ++i)
      if (frontier & (1ull << i)) next |= s.adj[i];
    frontier = next & ~visited;
  }
  return false;
}

// The acquisition hook: validate `cls` against every held lock, then
// push the held record. `addr` is the lock object (for release
// matching and same-instance diagnostics).
inline void OnAcquire(int cls, const void* addr, bool shared) {
  State& s = state();
  ThreadHeld& th = held();
  Stack cur;
  cur.Capture();
  if (th.n >= kMaxHeld) {
    std::fprintf(stderr,
                 "ptpu_lockdep: more than %d locks held by one thread "
                 "(acquiring \"%s\")\n",
                 kMaxHeld, s.classes[cls].name);
    PrintStack("of the over-deep acquisition", cur);
    ReportEnd();
    return;
  }
  const ClassInfo& ci = s.classes[cls];
  for (int i = 0; i < th.n; ++i) {
    const HeldLock& hl = th.h[i];
    const ClassInfo& hc = s.classes[hl.cls];
    if (hl.cls == cls) {
      std::fprintf(
          stderr,
          "== ptpu_lockdep: same-class recursion ==\n"
          "acquiring lock class \"%s\" (rank %d) while already "
          "holding %s instance of \"%s\"\n",
          ci.name, ci.rank, hl.addr == addr ? "THE SAME" : "another",
          hc.name);
      PrintStack("of the current acquisition", cur);
      PrintStack("of the already-held acquisition", hl.stack);
      ReportEnd();
      continue;
    }
    // ---- acquisition-order graph: edge hl.cls -> cls ----
    bool cycle = false, rank_bad = ci.rank <= hc.rank;
    Edge evid;  // opposite-direction evidence for the report
    {
      std::lock_guard<std::mutex> g(s.mu);
      if (Reaches(s, cls, hl.cls)) {
        cycle = true;
        evid = s.edges[cls * kMaxClasses + hl.cls];
      }
      Edge& e = s.edges[hl.cls * kMaxClasses + cls];
      if (!e.present) {
        e.present = true;
        e.from_stack = hl.stack;
        e.to_stack = cur;
        s.adj[hl.cls] |= 1ull << cls;
      }
    }
    if (cycle) {
      std::fprintf(
          stderr,
          "== ptpu_lockdep: lock-order cycle (ABBA deadlock) ==\n"
          "acquiring \"%s\" (rank %d) while holding \"%s\" (rank %d): "
          "the opposite order \"%s\" -> ... -> \"%s\" was recorded "
          "earlier\n",
          ci.name, ci.rank, hc.name, hc.rank, ci.name, hc.name);
      PrintStack("of the current acquisition", cur);
      PrintStack("of the held lock's acquisition", hl.stack);
      if (evid.present) {
        PrintStack("of the earlier direct edge: holder", evid.from_stack);
        PrintStack("of the earlier direct edge: acquirer", evid.to_stack);
      }
      ReportEnd();
    } else if (rank_bad) {
      std::fprintf(
          stderr,
          "== ptpu_lockdep: rank-order violation ==\n"
          "acquiring \"%s\" (rank %d) while holding \"%s\" (rank %d) "
          "— ranks must strictly increase along any nesting "
          "(declare the intended order in the PTPU_LOCK_CLASS table)\n",
          ci.name, ci.rank, hc.name, hc.rank);
      PrintStack("of the current acquisition", cur);
      PrintStack("of the held lock's acquisition", hl.stack);
      ReportEnd();
    }
  }
  HeldLock& rec = th.h[th.n++];
  rec.cls = cls;
  rec.addr = addr;
  rec.shared = shared;
  rec.stack = cur;
}

inline void OnRelease(int cls, const void* addr) {
  ThreadHeld& th = held();
  for (int i = th.n - 1; i >= 0; --i) {
    if (th.h[i].addr == addr && th.h[i].cls == cls) {
      for (int k = i; k + 1 < th.n; ++k) th.h[k] = th.h[k + 1];
      --th.n;
      return;
    }
  }
  std::fprintf(stderr,
               "ptpu_lockdep: releasing \"%s\" that this thread does "
               "not hold\n",
               state().classes[cls].name);
  Stack cur;
  cur.Capture();
  PrintStack("of the bogus release", cur);
  ReportEnd();
}

// A blocking wait is about to sleep with `self` released by the wait:
// every OTHER held lock must be kLockAllowBlock.
inline void OnBlockingWait(const void* self) {
  State& s = state();
  ThreadHeld& th = held();
  for (int i = 0; i < th.n; ++i) {
    const HeldLock& hl = th.h[i];
    if (hl.addr == self) continue;
    const ClassInfo& hc = s.classes[hl.cls];
    if (hc.flags & kLockAllowBlock) continue;
    Stack cur;
    cur.Capture();
    std::fprintf(
        stderr,
        "== ptpu_lockdep: lock held across a blocking wait ==\n"
        "waiting on a condition variable while holding \"%s\" "
        "(rank %d), a class not marked kLockAllowBlock — every "
        "waiter on that lock now sleeps too\n",
        hc.name, hc.rank);
    PrintStack("of the blocking wait", cur);
    PrintStack("of the held lock's acquisition", hl.stack);
    ReportEnd();
  }
}

// Handler-boundary invariant (used by the net core before dispatching
// a frame handler, and by the batcher before invoking a runner): the
// calling thread must hold NO lockdep-tracked lock at all.
inline void AssertNoLocksHeld(const char* what) {
  ThreadHeld& th = held();
  if (th.n == 0) return;
  Stack cur;
  cur.Capture();
  std::fprintf(stderr,
               "== ptpu_lockdep: locks held entering %s ==\n"
               "\"%s\" (and %d other(s)) held at a boundary that "
               "requires none\n",
               what, state().classes[th.h[0].cls].name, th.n - 1);
  PrintStack("of the boundary", cur);
  PrintStack("of the held lock's acquisition", th.h[0].stack);
  ReportEnd();
}

inline uint64_t ViolationCount() {
  return state().violations.load(std::memory_order_relaxed);
}

}  // namespace lockdep

// A named, ranked lock class (one per LOGICAL lock, shared by all its
// instances — e.g. every connection's out-lock is one class).
class LockClass {
 public:
  LockClass(const char* name, int rank, unsigned flags = 0)
      : id_(lockdep::RegisterClass(name, rank, flags)) {}
  int id() const { return id_; }

 private:
  int id_;
};

#define PTPU_LOCKDEP_ASSERT_NO_LOCKS(what) \
  ::ptpu::lockdep::AssertNoLocksHeld(what)

#else  // !PTPU_LOCKDEP ------------------------------------------------

// Shipping pass-through: a LockClass carries nothing and the wrappers
// below compile to the bare std:: primitive.
class LockClass {
 public:
  constexpr LockClass(const char*, int, unsigned = 0) {}
};

#define PTPU_LOCKDEP_ASSERT_NO_LOCKS(what) ((void)0)

#endif  // PTPU_LOCKDEP

// Declare a lock class: PTPU_LOCK_CLASS(kFooClass, "subsys.foo", 40)
// (+ optional ::ptpu::kLockAllowBlock). The `sync` checker in
// tools/ptpu_check.py requires every class declaration to carry a
// numeric rank and every ptpu::Mutex/SharedMutex to name its class.
#define PTPU_LOCK_CLASS(var, name, ...) \
  inline ::ptpu::LockClass var { name, __VA_ARGS__ }

// ---------------------------------------------------------------------------
// Mutex / SharedMutex / CondVar wrappers
// ---------------------------------------------------------------------------

class Mutex {
 public:
#if defined(PTPU_LOCKDEP)
  explicit Mutex(LockClass& c) : cls_(&c) {}
  void lock() {
#if defined(PTPU_SCHEDCK)
    if (schedck::OnMutexLock(this)) {
      lockdep::OnAcquire(cls_->id(), this, /*shared=*/false);
      return;
    }
#endif
    m_.lock();
    lockdep::OnAcquire(cls_->id(), this, /*shared=*/false);
  }
  bool try_lock() {
#if defined(PTPU_SCHEDCK)
    bool acq = false;
    if (schedck::OnMutexTryLock(this, &acq)) {
      if (acq) lockdep::OnAcquire(cls_->id(), this, /*shared=*/false);
      return acq;
    }
#endif
    if (!m_.try_lock()) return false;
    lockdep::OnAcquire(cls_->id(), this, /*shared=*/false);
    return true;
  }
  void unlock() {
    lockdep::OnRelease(cls_->id(), this);
#if defined(PTPU_SCHEDCK)
    if (schedck::OnMutexUnlock(this)) return;
#endif
    m_.unlock();
  }
#else
  explicit Mutex(LockClass&) {}
  void lock() {
#if defined(PTPU_SCHEDCK)
    if (schedck::OnMutexLock(this)) return;
#endif
    m_.lock();
  }
  bool try_lock() {
#if defined(PTPU_SCHEDCK)
    bool acq = false;
    if (schedck::OnMutexTryLock(this, &acq)) return acq;
#endif
    return m_.try_lock();
  }
  void unlock() {
#if defined(PTPU_SCHEDCK)
    if (schedck::OnMutexUnlock(this)) return;
#endif
    m_.unlock();
  }
#endif
  std::mutex& native() { return m_; }

 private:
  friend class CondVar;
#if defined(PTPU_LOCKDEP)
  LockClass* cls_;
#endif
  std::mutex m_;
};

class SharedMutex {
 public:
#if defined(PTPU_LOCKDEP)
  explicit SharedMutex(LockClass& c) : cls_(&c) {}
  void lock() {
#if defined(PTPU_SCHEDCK)
    if (schedck::OnSharedLock(this)) {
      lockdep::OnAcquire(cls_->id(), this, /*shared=*/false);
      return;
    }
#endif
    m_.lock();
    lockdep::OnAcquire(cls_->id(), this, /*shared=*/false);
  }
  void unlock() {
    lockdep::OnRelease(cls_->id(), this);
#if defined(PTPU_SCHEDCK)
    if (schedck::OnSharedUnlock(this)) return;
#endif
    m_.unlock();
  }
  void lock_shared() {
#if defined(PTPU_SCHEDCK)
    if (schedck::OnSharedLockShared(this)) {
      lockdep::OnAcquire(cls_->id(), this, /*shared=*/true);
      return;
    }
#endif
    m_.lock_shared();
    lockdep::OnAcquire(cls_->id(), this, /*shared=*/true);
  }
  void unlock_shared() {
    lockdep::OnRelease(cls_->id(), this);
#if defined(PTPU_SCHEDCK)
    if (schedck::OnSharedUnlockShared(this)) return;
#endif
    m_.unlock_shared();
  }
#else
  explicit SharedMutex(LockClass&) {}
  void lock() {
#if defined(PTPU_SCHEDCK)
    if (schedck::OnSharedLock(this)) return;
#endif
    m_.lock();
  }
  void unlock() {
#if defined(PTPU_SCHEDCK)
    if (schedck::OnSharedUnlock(this)) return;
#endif
    m_.unlock();
  }
  void lock_shared() {
#if defined(PTPU_SCHEDCK)
    if (schedck::OnSharedLockShared(this)) return;
#endif
    m_.lock_shared();
  }
  void unlock_shared() {
#if defined(PTPU_SCHEDCK)
    if (schedck::OnSharedUnlockShared(this)) return;
#endif
    m_.unlock_shared();
  }
#endif

 private:
#if defined(PTPU_LOCKDEP)
  LockClass* cls_;
#endif
  std::shared_mutex m_;
};

using MutexLock = std::lock_guard<Mutex>;
using UniqueLock = std::unique_lock<Mutex>;
using SharedLock = std::shared_lock<SharedMutex>;
using SharedUniqueLock = std::unique_lock<SharedMutex>;

class CondVar {
 public:
  void notify_one() {
#if defined(PTPU_SCHEDCK)
    if (schedck::OnCvNotify(this)) return;
#endif
    cv_.notify_one();
  }
  void notify_all() {
#if defined(PTPU_SCHEDCK)
    if (schedck::OnCvNotify(this)) return;
#endif
    cv_.notify_all();
  }

  // Untimed wait WITH predicate (the only public untimed form: a
  // predicate-free wait returns on spurious wakeups unchecked — the
  // `locks` lint bans it outside this header).
  template <class Pred>
  void wait(UniqueLock& l, Pred pred) {
    while (!pred()) WaitImpl(l, -1);
  }

 private:
  // Timed wait without predicate: callers MUST loop on their own
  // predicate/deadline around this (spurious wakeups). Accessed via
  // ptpu::CvWaitForUs below.
  void WaitImpl(UniqueLock& l, int64_t usec) {
    Mutex* m = l.mutex();
#if defined(PTPU_LOCKDEP)
    lockdep::OnBlockingWait(m);
    // the wait releases and reacquires m: mirror that in the held
    // set so the reacquisition re-validates order against anything
    // still held
    lockdep::OnRelease(m->cls_->id(), m);
#endif
#if defined(PTPU_SCHEDCK)
    // Managed threads never touched the real m->m_ (Mutex::lock was
    // modeled too), so the wait/release/reacquire cycle is pure model
    // state. usec semantics: <0 untimed (re-enabled only by notify),
    // >=0 timed (the scheduler may elect the timeout at any decision).
    if (schedck::OnCvWait(this, m, usec)) {
#if defined(PTPU_LOCKDEP)
      lockdep::OnAcquire(m->cls_->id(), m, /*shared=*/false);
#endif
      return;
    }
#endif
    {
      std::unique_lock<std::mutex> il(m->native(), std::adopt_lock);
      if (usec < 0) {
        cv_.wait(il);
      } else {
#if defined(PTPU_TSAN_BUILD)
        cv_.wait_until(il, std::chrono::system_clock::now() +
                               std::chrono::microseconds(usec));
#else
        cv_.wait_for(il, std::chrono::microseconds(usec));
#endif
      }
      il.release();
    }
#if defined(PTPU_LOCKDEP)
    lockdep::OnAcquire(m->cls_->id(), m, /*shared=*/false);
#endif
  }

  friend void CvWaitForUs(CondVar&, UniqueLock&, int64_t);
  std::condition_variable cv_;
};

// Timed wait without predicate: the caller MUST loop on its own
// predicate/deadline around this (condvar waits wake spuriously).
inline void CvWaitForUs(CondVar& cv, UniqueLock& l, int64_t usec) {
  cv.WaitImpl(l, usec);
}

// Timed wait with predicate; returns the predicate's final value
// (false == timed out with the predicate still unsatisfied).
template <class Pred>
inline bool CvWaitForUs(CondVar& cv, UniqueLock& l, int64_t usec,
                        Pred pred) {
  const auto deadline =
      std::chrono::steady_clock::now() + std::chrono::microseconds(usec);
  while (!pred()) {
    const auto now = std::chrono::steady_clock::now();
    if (now >= deadline) return pred();
    CvWaitForUs(cv, l,
                std::chrono::duration_cast<std::chrono::microseconds>(
                    deadline - now)
                    .count());
  }
  return true;
}

}  // namespace ptpu

#endif  // PTPU_SYNC_H_
