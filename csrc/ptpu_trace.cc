// Implementation of the shared span recorder + the C Prometheus
// renderer (see ptpu_trace.h). Compiled into BOTH shipping server .so
// artifacts and single-TU-included by the selftests.
#include "ptpu_trace.h"

#include <cstdlib>
#include <cstring>
#include <random>

#include "ptpu_schedck.h"
#include "ptpu_stats.h"

namespace ptpu {
namespace trace {

// Twin map: paddle_tpu/profiler/timeline.py SPAN_KIND_NAMES (the
// `trace` checker in tools/ptpu_check.py enforces the parity).
const char* const kSpanKindNames[kKindCount] = {
    "net.read",      // kRead
    "batch.queue",   // kQueue
    "batch.fill",    // kBatch
    "predictor.run", // kRun
    "net.flush",     // kFlush
    "ps.pull",       // kPull
    "ps.push",       // kPush
    "decode.step",   // kDecode
};

namespace {

int64_t EnvI64(const char* name, int64_t dflt) {
  const char* e = std::getenv(name);
  if (!e || !*e) return dflt;
  char* end = nullptr;
  const long long v = std::strtoll(e, &end, 10);
  return (end && *end == '\0') ? int64_t(v) : dflt;
}

size_t RoundPow2(size_t v, size_t lo, size_t hi) {
  size_t p = lo;
  while (p < v && p < hi) p <<= 1;
  return p;
}

}  // namespace

Config ConfigFromEnv() {
  Config c;
  c.sample = EnvI64("PTPU_TRACE_SAMPLE", c.sample);
  c.slow_us = EnvI64("PTPU_TRACE_SLOW_US", c.slow_us);
  c.ring = size_t(EnvI64("PTPU_TRACE_RING", int64_t(c.ring)));
  return c;
}

Recorder::Recorder(const Config& cfg)
    : sample_(cfg.sample),
      slow_us_(cfg.slow_us),
      ring_(RoundPow2(cfg.ring, 64, 1u << 20)),
      slow_(RoundPow2(cfg.slow_ring, 8, 1u << 12)) {
  // seed the id mixer once (construction is cold; ids must differ
  // across processes so merged traces never collide)
  std::random_device rd;
  seed_ = (uint64_t(rd()) << 32) | rd();
}

uint64_t Recorder::NewTraceId() {
  // splitmix64 over a claimed counter: unique per recorder, cheap,
  // and never 0 after the final fixup (0 means "untraced")
  uint64_t z =
      id_ctr_.fetch_add(1, std::memory_order_relaxed) + seed_ +
      0x9e3779b97f4a7c15ull;
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ull;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebull;
  z ^= z >> 31;
  return z ? z : 1;
}

void Recorder::Record(uint64_t tid, uint8_t kind, int64_t t0_us,
                      int64_t t1_us, uint64_t conn, uint64_t arg) {
  if (!tid) return;
  const uint64_t idx = head_.fetch_add(1, std::memory_order_relaxed);
  Slot& s = ring_[idx & (ring_.size() - 1)];
  /* Seqlock write bracket (Boehm, "Can seqlocks get along with
   * programming language memory models"): the release FENCE keeps the
   * odd marker visible before any field store (a release STORE alone
   * orders only prior accesses — the relaxed field writes could hoist
   * above it), and the final release store keeps every field before
   * the even marker. Readers mirror with an acquire fence. */
  s.seq.store(2 * idx + 1, std::memory_order_relaxed);
  std::atomic_thread_fence(std::memory_order_release);
  PTPU_SCHED_POINT();  // mid-bracket: fields half-written, seq odd
  s.trace_id.store(tid, std::memory_order_relaxed);
  s.kind.store(kind, std::memory_order_relaxed);
  s.t0.store(t0_us, std::memory_order_relaxed);
  s.t1.store(t1_us, std::memory_order_relaxed);
  PTPU_SCHED_POINT();  // fields written, even marker not yet visible
  s.conn.store(conn, std::memory_order_relaxed);
  s.arg.store(arg, std::memory_order_relaxed);
  s.seq.store(2 * idx + 2, std::memory_order_release);
}

void Recorder::RecordSlow(uint64_t tid, uint64_t conn, uint64_t req,
                          int64_t e2e_us, const SpanRec* spans, int n) {
  const uint64_t idx =
      slow_head_.fetch_add(1, std::memory_order_relaxed);
  SlowSlot& s = slow_[idx & (slow_.size() - 1)];
  s.seq.store(2 * idx + 1, std::memory_order_relaxed);
  std::atomic_thread_fence(std::memory_order_release);
  s.trace_id.store(tid, std::memory_order_relaxed);
  s.conn.store(conn, std::memory_order_relaxed);
  s.req.store(req, std::memory_order_relaxed);
  s.e2e.store(e2e_us, std::memory_order_relaxed);
  const int keep = n < kSlowSpans ? n : kSlowSpans;
  s.n.store(keep, std::memory_order_relaxed);
  for (int i = 0; i < keep; ++i) {
    s.kind[i].store(spans[i].kind, std::memory_order_relaxed);
    s.t0[i].store(spans[i].t0_us, std::memory_order_relaxed);
    s.t1[i].store(spans[i].t1_us, std::memory_order_relaxed);
  }
  s.seq.store(2 * idx + 2, std::memory_order_release);
}

void Recorder::Set(int64_t sample, int64_t slow_us) {
  if (sample >= 0)
    sample_.store(sample, std::memory_order_relaxed);
  if (slow_us >= 0)
    slow_us_.store(slow_us, std::memory_order_relaxed);
}

void Recorder::Snapshot(std::vector<SpanView>* out,
                        size_t max_n) const {
  out->clear();
  const uint64_t head = head_.load(std::memory_order_acquire);
  const size_t n =
      size_t(head < ring_.size() ? head : ring_.size());
  const size_t want = max_n < n ? max_n : n;
  out->reserve(want);
  for (size_t i = 0; i < want; ++i) {
    const uint64_t idx = head - 1 - i;
    const Slot& s = ring_[idx & (ring_.size() - 1)];
    if (s.seq.load(std::memory_order_acquire) != 2 * idx + 2)
      continue;  // torn (being overwritten right now): skip
    PTPU_SCHED_POINT();  // a writer may reclaim the slot mid-copy
    SpanView v;
    v.trace_id = s.trace_id.load(std::memory_order_relaxed);
    v.kind = s.kind.load(std::memory_order_relaxed);
    v.t0_us = s.t0.load(std::memory_order_relaxed);
    v.t1_us = s.t1.load(std::memory_order_relaxed);
    PTPU_SCHED_POINT();  // mid-copy: the re-check below must catch it
    v.conn = s.conn.load(std::memory_order_relaxed);
    v.arg = s.arg.load(std::memory_order_relaxed);
    // the acquire fence pins the field loads BEFORE the re-check (an
    // acquire load alone would let them sink past it)
    std::atomic_thread_fence(std::memory_order_acquire);
    if (s.seq.load(std::memory_order_relaxed) != 2 * idx + 2)
      continue;  // overwritten while copying
    out->push_back(v);
  }
}

void Recorder::SnapshotSlow(std::vector<SlowView>* out) const {
  out->clear();
  const uint64_t head = slow_head_.load(std::memory_order_acquire);
  const size_t n =
      size_t(head < slow_.size() ? head : slow_.size());
  out->reserve(n);
  for (size_t i = 0; i < n; ++i) {
    const uint64_t idx = head - 1 - i;
    const SlowSlot& s = slow_[idx & (slow_.size() - 1)];
    if (s.seq.load(std::memory_order_acquire) != 2 * idx + 2)
      continue;
    SlowView v;
    v.trace_id = s.trace_id.load(std::memory_order_relaxed);
    v.conn = s.conn.load(std::memory_order_relaxed);
    v.req = s.req.load(std::memory_order_relaxed);
    v.e2e_us = s.e2e.load(std::memory_order_relaxed);
    const int cnt = s.n.load(std::memory_order_relaxed);
    for (int k = 0; k < cnt && k < kSlowSpans; ++k) {
      SpanView sp;
      sp.kind = s.kind[k].load(std::memory_order_relaxed);
      sp.t0_us = s.t0[k].load(std::memory_order_relaxed);
      sp.t1_us = s.t1[k].load(std::memory_order_relaxed);
      v.spans.push_back(sp);
    }
    std::atomic_thread_fence(std::memory_order_acquire);
    if (s.seq.load(std::memory_order_relaxed) != 2 * idx + 2)
      continue;
    out->push_back(std::move(v));
  }
}

namespace {

const char* KindName(uint8_t k) {
  return k < kKindCount ? kSpanKindNames[k] : "unknown";
}

void AppendSpan(std::string* out, const SpanView& v, bool full) {
  *out += "{\"kind\":\"";
  *out += KindName(v.kind);
  *out += "\",";
  AppendJsonU64(out, "t0_us", uint64_t(v.t0_us));
  *out += ',';
  AppendJsonU64(out, "t1_us", uint64_t(v.t1_us));
  if (full) {
    *out += ',';
    AppendJsonU64(out, "trace_id", v.trace_id);
    *out += ',';
    AppendJsonU64(out, "conn", v.conn);
    *out += ',';
    AppendJsonU64(out, "arg", v.arg);
  }
  *out += '}';
}

}  // namespace

std::string Recorder::TracezJson(size_t max_n) const {
  std::vector<SpanView> spans;
  Snapshot(&spans, max_n);
  std::vector<SlowView> slow;
  SnapshotSlow(&slow);
  std::string out = "{";
  AppendJsonU64(&out, "sample", uint64_t(sample()));
  out += ',';
  AppendJsonU64(&out, "slow_us", uint64_t(slow_us()));
  out += ',';
  AppendJsonU64(&out, "ring", uint64_t(ring_.size()));
  out += ',';
  AppendJsonU64(&out, "recorded", recorded());
  out += ",\"spans\":[";
  for (size_t i = 0; i < spans.size(); ++i) {
    if (i) out += ',';
    AppendSpan(&out, spans[i], /*full=*/true);
  }
  out += "],\"slow\":[";
  for (size_t i = 0; i < slow.size(); ++i) {
    if (i) out += ',';
    const SlowView& v = slow[i];
    out += '{';
    AppendJsonU64(&out, "trace_id", v.trace_id);
    out += ',';
    AppendJsonU64(&out, "conn", v.conn);
    out += ',';
    AppendJsonU64(&out, "req", v.req);
    out += ',';
    AppendJsonU64(&out, "e2e_us", uint64_t(v.e2e_us));
    out += ",\"spans\":[";
    for (size_t k = 0; k < v.spans.size(); ++k) {
      if (k) out += ',';
      AppendSpan(&out, v.spans[k], /*full=*/false);
    }
    out += "]}";
  }
  out += "]}";
  return out;
}

Recorder& Global() {
  /* Immortal on purpose (fuzzing finding, ISSUE 11): a function-local
   * static is destroyed by __run_exit_handlers, but server/batcher
   * threads may still be RECORDING at process exit whenever the
   * embedding process exits without ptpu_serving_stop /
   * ptpu_ps_server_stop (abrupt exit is a legal shutdown path) —
   * ASan-caught heap-use-after-free in Record() against the
   * destructed ring. The standard logger/recorder fix: heap-allocate
   * once and never destroy; still reachable through this pointer, so
   * LSan stays quiet. */
  static Recorder* g = new Recorder(ConfigFromEnv());
  return *g;
}

// ---------------------------------------------------------------------------
// Prometheus renderer — a restricted JSON reader over the stats
// snapshots OUR renderers emit (objects, unsigned integers, arrays of
// unsigned integers, escaped strings), walked exactly like
// profiler/stats.py::prometheus_text so the two outputs are
// byte-identical for the same snapshot.
// ---------------------------------------------------------------------------

namespace {

// the reader itself lives header-only in ptpu_trace.h (rj::) — the
// ptpu_invar conservation-law engine walks the same fuzzed parser
using rj::HistField;
using rj::IsHist;
using rj::JNode;
using rj::JParser;

std::string PromName(const std::string& prefix,
                     const std::vector<std::string>& path,
                     const std::string& leaf) {
  // python twin: "_".join(non-empty parts), then sanitize
  std::string name;
  const auto add = [&name](const std::string& s) {
    if (s.empty()) return;
    if (!name.empty()) name += '_';
    name += s;
  };
  add(prefix);
  for (const auto& p : path) add(p);
  add(leaf);
  for (auto& ch : name)
    if (!((ch >= 'a' && ch <= 'z') || (ch >= 'A' && ch <= 'Z') ||
          (ch >= '0' && ch <= '9') || ch == '_'))
      ch = '_';
  return name;
}

struct PromWalk {
  std::string out;
  std::vector<std::string> seen_type;  // families with a TYPE line

  bool Seen(const std::string& name) {
    for (const auto& s : seen_type)
      if (s == name) return true;
    seen_type.push_back(name);
    return false;
  }

  void Emit(const std::string& name, const JNode& v,
            const std::string& labels) {
    if (IsHist(v)) {
      if (!Seen(name))
        out += "# TYPE " + name + " histogram\n";
      const JNode* buckets = HistField(v, "buckets");
      const JNode* sum = HistField(v, "sum");
      const JNode* count = HistField(v, "count");
      uint64_t cum = 0;
      const size_t nb = buckets->arr.size();
      for (size_t b = 0; b < nb; ++b) {
        cum += buckets->arr[b];
        std::string le;
        if (b == 0) {
          le = "0";
        } else if (b == nb - 1) {
          le = "+Inf";
        } else {
          // log2 bucket b covers [2^(b-1), 2^b): upper edge 2^b - 1
          le = std::to_string((uint64_t(1) << b) - 1);
        }
        out += name + "_bucket{" + labels +
               (labels.empty() ? "" : ",") + "le=\"" + le + "\"} " +
               std::to_string(cum) + "\n";
      }
      if (labels.empty()) {
        out += name + "_sum " + std::to_string(sum->num) + "\n";
        out += name + "_count " + std::to_string(count->num) + "\n";
      } else {
        out += name + "_sum{" + labels + "} " +
               std::to_string(sum->num) + "\n";
        out += name + "_count{" + labels + "} " +
               std::to_string(count->num) + "\n";
      }
    } else {
      if (!Seen(name))
        out += "# TYPE " + name + " counter\n";
      if (labels.empty())
        out += name + " " + std::to_string(v.num) + "\n";
      else
        out += name + "{" + labels + "} " + std::to_string(v.num) +
               "\n";
    }
  }

  void Walk(const std::string& prefix, std::vector<std::string>& path,
            const JNode& node, const std::string& labels) {
    for (const auto& kv : node.obj) {
      const std::string& k = kv.first;
      const JNode& v = kv.second;
      if (k == "tables" && v.kind == JNode::kObj && !IsHist(v)) {
        for (const auto& tkv : v.obj) {
          path.push_back("table");
          std::string lbl = labels + (labels.empty() ? "" : ",") +
                            "table=\"" + tkv.first + "\"";
          Walk(prefix, path, tkv.second, lbl);
          path.pop_back();
        }
      } else if (v.kind == JNode::kObj && !IsHist(v)) {
        path.push_back(k);
        Walk(prefix, path, v, labels);
        path.pop_back();
      } else if (v.kind == JNode::kNum || IsHist(v)) {
        Emit(PromName(prefix, path, k), v, labels);
      }
      // strings / number arrays outside a histogram: not metrics
    }
  }
};

}  // namespace

std::string PromFromStatsJson(const std::string& stats_json,
                              const std::string& prefix) {
  JParser jp{stats_json.data(),
             stats_json.data() + stats_json.size()};
  JNode root = jp.Value(0);
  if (!jp.ok || root.kind != JNode::kObj)
    return "# ptpu: stats snapshot did not parse\n";
  PromWalk w;
  std::vector<std::string> path;
  w.Walk(prefix, path, root, "");
  return w.out;
}

}  // namespace trace
}  // namespace ptpu

// Runtime tracing override, exported from every .so that links this
// TU: sample < 0 / slow_us < 0 keep the current value. Tests and
// operators flip sampling without a restart (the env knobs
// PTPU_TRACE_SAMPLE / PTPU_TRACE_SLOW_US only apply at first touch).
extern "C" __attribute__((visibility("default"))) void ptpu_trace_set(
    int64_t sample, int64_t slow_us) {
  ptpu::trace::Global().Set(sample, slow_us);
}

// Read-side twin for bindings without HTTP: the /tracez JSON.
// Thread-local buffer, valid until the calling thread's next call.
extern "C" __attribute__((visibility("default"))) const char*
ptpu_trace_json(int64_t max_spans) {
  thread_local std::string buf;
  buf = ptpu::trace::Global().TracezJson(
      max_spans > 0 ? size_t(max_spans) : 128);
  return buf.c_str();
}
