// Native unit tests for the predictor TU internals — the cc_test
// analogue (reference: gtest cc_test targets per CMakeLists, e.g.
// `paddle/fluid/framework/data_type_test.cc`). Plain asserts, no test
// framework dependency; exit 0 = pass. Includes the predictor TU
// directly so the anonymous-namespace kernels (sgemm/igemm/bcast_walk/
// int8_exact/check_dims) are testable without widening their linkage.
//
// Build + run: make selftest (csrc/Makefile); wrapped by
// tests/test_native_selftest.py.
#include "ptpu_predictor.cc"

// asserts ARE the test — never compile them out, even under a
// release-style CXXFLAGS override carrying -DNDEBUG
#undef NDEBUG
#include <cassert>
#include <cstdio>
#include <random>

namespace {

void test_sgemm_matches_naive() {
  std::mt19937 rng(0);
  std::uniform_real_distribution<float> d(-1.f, 1.f);
  const int64_t M = 17, N = 33, K = 29;
  std::vector<float> A(M * K), B(K * N), C(M * N), ref(M * N, 0.f);
  for (auto& v : A) v = d(rng);
  for (auto& v : B) v = d(rng);
  sgemm(A.data(), B.data(), C.data(), M, N, K);
  for (int64_t m = 0; m < M; ++m)
    for (int64_t j = 0; j < N; ++j) {
      float acc = 0.f;
      for (int64_t k = 0; k < K; ++k) acc += A[m * K + k] * B[k * N + j];
      ref[m * N + j] = acc;
    }
  for (int64_t i = 0; i < M * N; ++i)
    assert(std::fabs(C[i] - ref[i]) <= 1e-4f * (1.f + std::fabs(ref[i])));
}

void test_sgemm_propagates_nan_through_zero() {
  // IEEE: 0 * NaN must stay NaN (the zero-skip regression guard)
  const float nan = std::nanf("");
  std::vector<float> A{0.f, 1.f}, B{nan, 2.f}, C(1);
  sgemm(A.data(), B.data(), C.data(), 1, 1, 2);
  assert(std::isnan(C[0]));
}

void test_igemm_exact() {
  std::mt19937 rng(1);
  std::uniform_int_distribution<int> d(-128, 127);
  const int64_t M = 9, N = 13, K = 21;
  std::vector<int32_t> A(M * K), B(K * N), C(M * N);
  for (auto& v : A) v = d(rng);
  for (auto& v : B) v = d(rng);
  igemm(A.data(), B.data(), C.data(), M, N, K);
  for (int64_t m = 0; m < M; ++m)
    for (int64_t j = 0; j < N; ++j) {
      int64_t acc = 0;
      for (int64_t k = 0; k < K; ++k)
        acc += int64_t(A[m * K + k]) * B[k * N + j];
      assert(C[m * N + j] == acc);
    }
}

void test_int8_exact_bounds() {
  std::vector<int64_t> ok{-128, 127, 0}, bad{-129}, big{128};
  const int64_t kmax = (int64_t(1) << 31) / (128 * 128);
  assert(int8_exact(ok, ok, kmax - 1));
  assert(!int8_exact(ok, ok, kmax));      // strict: 2^31 would overflow
  assert(!int8_exact(bad, ok, 4));
  assert(!int8_exact(ok, big, 4));
}

void test_bcast_walk_matches_divmod() {
  // [2,3,4] (x) [3,1] -> [2,3,4]; compare odometer against bcast_index
  std::vector<int64_t> od{2, 3, 4}, ad{2, 3, 4}, bd{3, 1};
  bcast_walk(od, ad, bd, [&](int64_t k, int64_t ai, int64_t bi) {
    assert(ai == bcast_index(k, od, ad));
    assert(bi == bcast_index(k, od, bd));
  });
  // scalar operand
  std::vector<int64_t> sd{};
  bcast_walk(od, ad, sd, [&](int64_t, int64_t, int64_t bi) {
    assert(bi == 0);
  });
}

void test_check_dims_rejects() {
  int64_t neg[2] = {2, -1};
  bool threw = false;
  try {
    check_dims(neg, 2);
  } catch (const std::exception&) {
    threw = true;
  }
  assert(threw);
  int64_t huge[2] = {3037000500LL, 3037000500LL};
  threw = false;
  try {
    check_dims(huge, 2);
  } catch (const std::exception&) {
    threw = true;
  }
  assert(threw);
  check_dims(nullptr, 0);  // 0-d scalar is legal
}

void test_parallel_for_covers_range() {
  std::vector<int> hit(1000, 0);
  parallel_for(1000, 7, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) hit[size_t(i)]++;
  });
  for (int v : hit) assert(v == 1);
}

/* Fringe sweep for the packed cache-blocked GEMM: every (M % MR,
 * N % NR) combination plus K crossing a KC boundary must match the
 * naive triple loop — the panel zero-padding and partial-tile
 * load/store paths are all exercised. */
void test_packed_gemm_fringe_sweep() {
  std::mt19937 rng(7);
  std::uniform_real_distribution<float> d(-1.f, 1.f);
  for (int64_t M : {1, 5, 6, 7, 13}) {
    for (int64_t N : {1, 15, 16, 17, 33}) {
      for (int64_t K : {1, 31, 321}) {  // 321 crosses the KC=320 block
        std::vector<float> A(size_t(M * K)), B(size_t(K * N));
        std::vector<float> C(size_t(M * N), -7.f);
        for (auto& v : A) v = d(rng);
        for (auto& v : B) v = d(rng);
        sgemm(A.data(), B.data(), C.data(), M, N, K);
        for (int64_t m = 0; m < M; ++m)
          for (int64_t j = 0; j < N; ++j) {
            float acc = 0.f;
            for (int64_t k = 0; k < K; ++k)
              acc += A[size_t(m * K + k)] * B[size_t(k * N + j)];
            assert(std::fabs(C[size_t(m * N + j)] - acc) <=
                   2e-4f * (1.f + std::fabs(acc)));
          }
      }
    }
  }
}

/* The fused epilogue: bias-per-column + relu must equal gemm followed
 * by the separate add/max passes (the op-fusion contract). */
void test_gemm_bias_act_epilogue() {
  std::mt19937 rng(11);
  std::uniform_real_distribution<float> d(-1.f, 1.f);
  const int64_t M = 13, N = 21, K = 37;
  std::vector<float> A(size_t(M * K)), B(size_t(K * N));
  std::vector<float> bias(size_t(N), 0.f);
  std::vector<float> C(size_t(M * N)), R(size_t(M * N));
  for (auto& v : A) v = d(rng);
  for (auto& v : B) v = d(rng);
  for (auto& v : bias) v = d(rng);
  gemm_bias_act<float>(A.data(), B.data(), C.data(), M, N, K, nullptr,
                       nullptr, bias.data(), nullptr, ACT_RELU);
  sgemm(A.data(), B.data(), R.data(), M, N, K);
  for (int64_t m = 0; m < M; ++m)
    for (int64_t j = 0; j < N; ++j) {
      const float want =
          std::max(0.f, R[size_t(m * N + j)] + bias[size_t(j)]);
      assert(std::fabs(C[size_t(m * N + j)] - want) <= 1e-5f);
    }
  // bias per ROW (the conv layout)
  std::vector<float> bm(size_t(M), 0.f);
  for (auto& v : bm) v = d(rng);
  gemm_bias_act<float>(A.data(), B.data(), C.data(), M, N, K, nullptr,
                       nullptr, nullptr, bm.data(), ACT_NONE);
  for (int64_t m = 0; m < M; ++m)
    for (int64_t j = 0; j < N; ++j)
      assert(std::fabs(C[size_t(m * N + j)] -
                       (R[size_t(m * N + j)] + bm[size_t(m)])) <= 1e-5f);
}

/* WorkPool concurrency: two threads dispatching interleaved
 * parallel_for batches (two predictors serving concurrently — the r5
 * singleton race). Each thread owns a disjoint array; any cross-talk
 * between dispatches corrupts a counter. */
void test_workpool_two_thread_stress() {
  const int iters = 200;
  auto worker = [&](std::vector<int>* hits) {
    for (int it = 0; it < iters; ++it) {
      std::fill(hits->begin(), hits->end(), 0);
      parallel_for(int64_t(hits->size()), 3, [&](int64_t lo, int64_t hi) {
        for (int64_t i = lo; i < hi; ++i) (*hits)[size_t(i)]++;
      });
      for (int v : *hits) assert(v == 1);
    }
  };
  std::vector<int> h1(997, 0), h2(1501, 0);
  std::thread t1(worker, &h1), t2(worker, &h2);
  t1.join();
  t2.join();
}

/* PlanArena: disjoint lifetimes share offsets; the virtual size stays
 * at the peak, and freed space coalesces for bigger later tensors. */
void test_plan_arena_reuses_offsets() {
  ptpu::PlanArena a(64);
  const uint64_t o1 = a.Alloc(100);  // rounds to 128
  const uint64_t o2 = a.Alloc(50);
  a.Free(o1, 100);
  const uint64_t o3 = a.Alloc(100);  // must reuse o1's block
  assert(o3 == o1);
  a.Free(o2, 50);
  a.Free(o3, 100);
  const uint64_t o4 = a.Alloc(192);  // coalesced: fits in freed space
  assert(o4 == 0);
  assert(a.Size() == 192);  // 128 + 64, never grew past the peak
  // tail-aware growth: a partially-free tail extends instead of a
  // whole new block appended after it
  ptpu::PlanArena b(64);
  const uint64_t p1 = b.Alloc(64);
  b.Free(p1, 64);
  const uint64_t p2 = b.Alloc(128);  // reuses the 64-byte free tail
  assert(p2 == 0);
  assert(b.Size() == 128);
}

/* pack_b_im2col's segment emitter against the naive per-element
 * reference for strided + padded + dilated taps. */
void test_pack_b_im2col_matches_reference() {
  const int64_t ICG = 3, H = 7, W = 9, KH = 3, KW = 3;
  const int64_t sh = 2, sw = 1, ph = 1, pw = 2, dh = 1, dw = 2;
  const int64_t OH = (H + 2 * ph - dh * (KH - 1) - 1) / sh + 1;
  const int64_t OW = (W + 2 * pw - dw * (KW - 1) - 1) / sw + 1;
  const int64_t P = OH * OW, CK = ICG * KH * KW;
  std::vector<float> x(size_t(ICG * H * W));
  for (size_t k = 0; k < x.size(); ++k) x[k] = float(k) * 0.25f - 3.f;
  std::vector<float> packed(size_t(b_pack_size(CK, P)), -9.f);
  pack_b_im2col<float, float>(x.data(), ICG, H, W, KH, KW, OH, OW, sh, sw,
                              ph, pw, dh, dw, packed.data());
  for (int64_t r = 0; r < CK; ++r) {
    const int64_t ic = r / (KH * KW), kh = (r / KW) % KH, kw = r % KW;
    for (int64_t p = 0; p < P; ++p) {
      const int64_t oh = p / OW, ow = p % OW;
      const int64_t ih = oh * sh - ph + kh * dh;
      const int64_t iw = ow * sw - pw + kw * dw;
      const float want = (ih < 0 || ih >= H || iw < 0 || iw >= W)
                             ? 0.f
                             : x[size_t((ic * H + ih) * W + iw)];
      const float got =
          packed[size_t(((p / NR) * CK + r) * NR + (p % NR))];
      assert(got == want);
    }
  }
}

void test_predictor_run_stats_accumulate() {
  // hand-built one-node graph: run() must time the node, count the
  // run, and render it all in stats_json (the ABI the Python binding
  // parses); reset must zero it
  Predictor p;
  Node n;
  n.op = "Relu";
  n.inputs = {"x"};
  n.outputs = {"y"};
  p.g.nodes.push_back(n);
  p.g.output_names = {"y"};
  Tensor x;
  x.dtype = DT_F32;
  x.dims = {4};
  const std::vector<float> vals{-1.f, 2.f, -3.f, 4.f};
  x.f.assign(vals.begin(), vals.end());
  p.env["x"] = x;
  p.build_stats_index();
  p.run();
  p.env["x"] = x;
  p.run();
  assert(p.runs_ == 2);
  assert(p.op_stats_["Relu"].calls == 2);
  assert(p.op_stats_["Relu"].bytes == 2 * 4 * sizeof(float));
  assert(p.run_us_.count.load() == 2);
  const std::string j =
      ptpu_predictor_stats_json((PTPU_Predictor*)&p);
  assert(j.find("\"runs\":2") != std::string::npos);
  assert(j.find("\"Relu\"") != std::string::npos);
  assert(j.find("\"calls\":2") != std::string::npos);
  ptpu_predictor_stats_reset((PTPU_Predictor*)&p);
  assert(p.runs_ == 0 && p.op_stats_["Relu"].calls == 0);
}

}  // namespace

int main() {
  test_sgemm_matches_naive();
  test_sgemm_propagates_nan_through_zero();
  test_igemm_exact();
  test_int8_exact_bounds();
  test_bcast_walk_matches_divmod();
  test_check_dims_rejects();
  test_parallel_for_covers_range();
  test_packed_gemm_fringe_sweep();
  test_gemm_bias_act_epilogue();
  test_workpool_two_thread_stress();
  test_plan_arena_reuses_offsets();
  test_pack_b_im2col_matches_reference();
  test_predictor_run_stats_accumulate();
  std::printf("ptpu_selftest: all native unit tests passed\n");
  return 0;
}
