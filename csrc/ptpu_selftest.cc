// Native unit tests for the predictor TU internals — the cc_test
// analogue (reference: gtest cc_test targets per CMakeLists, e.g.
// `paddle/fluid/framework/data_type_test.cc`). Plain asserts, no test
// framework dependency; exit 0 = pass. Includes the predictor TU
// directly so the anonymous-namespace kernels (sgemm/igemm/bcast_walk/
// int8_exact/check_dims) are testable without widening their linkage.
//
// Build + run: make selftest (csrc/Makefile); wrapped by
// tests/test_native_selftest.py.
#include "ptpu_predictor.cc"

// asserts ARE the test — never compile them out, even under a
// release-style CXXFLAGS override carrying -DNDEBUG
#undef NDEBUG
#include <cassert>
#include <cstdio>
#include <random>

namespace {

void test_sgemm_matches_naive() {
  std::mt19937 rng(0);
  std::uniform_real_distribution<float> d(-1.f, 1.f);
  const int64_t M = 17, N = 33, K = 29;
  std::vector<float> A(M * K), B(K * N), C(M * N), ref(M * N, 0.f);
  for (auto& v : A) v = d(rng);
  for (auto& v : B) v = d(rng);
  sgemm(A.data(), B.data(), C.data(), M, N, K);
  for (int64_t m = 0; m < M; ++m)
    for (int64_t j = 0; j < N; ++j) {
      float acc = 0.f;
      for (int64_t k = 0; k < K; ++k) acc += A[m * K + k] * B[k * N + j];
      ref[m * N + j] = acc;
    }
  for (int64_t i = 0; i < M * N; ++i)
    assert(std::fabs(C[i] - ref[i]) <= 1e-4f * (1.f + std::fabs(ref[i])));
}

void test_sgemm_propagates_nan_through_zero() {
  // IEEE: 0 * NaN must stay NaN (the zero-skip regression guard)
  const float nan = std::nanf("");
  std::vector<float> A{0.f, 1.f}, B{nan, 2.f}, C(1);
  sgemm(A.data(), B.data(), C.data(), 1, 1, 2);
  assert(std::isnan(C[0]));
}

void test_igemm_exact() {
  std::mt19937 rng(1);
  std::uniform_int_distribution<int> d(-128, 127);
  const int64_t M = 9, N = 13, K = 21;
  std::vector<int32_t> A(M * K), B(K * N), C(M * N);
  for (auto& v : A) v = d(rng);
  for (auto& v : B) v = d(rng);
  igemm(A.data(), B.data(), C.data(), M, N, K);
  for (int64_t m = 0; m < M; ++m)
    for (int64_t j = 0; j < N; ++j) {
      int64_t acc = 0;
      for (int64_t k = 0; k < K; ++k)
        acc += int64_t(A[m * K + k]) * B[k * N + j];
      assert(C[m * N + j] == acc);
    }
}

void test_int8_exact_bounds() {
  std::vector<int64_t> ok{-128, 127, 0}, bad{-129}, big{128};
  const int64_t kmax = (int64_t(1) << 31) / (128 * 128);
  assert(int8_exact(ok, ok, kmax - 1));
  assert(!int8_exact(ok, ok, kmax));      // strict: 2^31 would overflow
  assert(!int8_exact(bad, ok, 4));
  assert(!int8_exact(ok, big, 4));
}

void test_bcast_walk_matches_divmod() {
  // [2,3,4] (x) [3,1] -> [2,3,4]; compare odometer against bcast_index
  std::vector<int64_t> od{2, 3, 4}, ad{2, 3, 4}, bd{3, 1};
  bcast_walk(od, ad, bd, [&](int64_t k, int64_t ai, int64_t bi) {
    assert(ai == bcast_index(k, od, ad));
    assert(bi == bcast_index(k, od, bd));
  });
  // scalar operand
  std::vector<int64_t> sd{};
  bcast_walk(od, ad, sd, [&](int64_t, int64_t, int64_t bi) {
    assert(bi == 0);
  });
}

void test_check_dims_rejects() {
  int64_t neg[2] = {2, -1};
  bool threw = false;
  try {
    check_dims(neg, 2);
  } catch (const std::exception&) {
    threw = true;
  }
  assert(threw);
  int64_t huge[2] = {3037000500LL, 3037000500LL};
  threw = false;
  try {
    check_dims(huge, 2);
  } catch (const std::exception&) {
    threw = true;
  }
  assert(threw);
  check_dims(nullptr, 0);  // 0-d scalar is legal
}

void test_parallel_for_covers_range() {
  std::vector<int> hit(1000, 0);
  parallel_for(1000, 7, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) hit[size_t(i)]++;
  });
  for (int v : hit) assert(v == 1);
}

/* Fringe sweep for the packed cache-blocked GEMM: every (M % MR,
 * N % NR) combination plus K crossing a KC boundary must match the
 * naive triple loop — the panel zero-padding and partial-tile
 * load/store paths are all exercised. */
void test_packed_gemm_fringe_sweep() {
  std::mt19937 rng(7);
  std::uniform_real_distribution<float> d(-1.f, 1.f);
  for (int64_t M : {1, 5, 6, 7, 13}) {
    for (int64_t N : {1, 15, 16, 17, 33}) {
      for (int64_t K : {1, 31, 321}) {  // 321 crosses the KC=320 block
        std::vector<float> A(size_t(M * K)), B(size_t(K * N));
        std::vector<float> C(size_t(M * N), -7.f);
        for (auto& v : A) v = d(rng);
        for (auto& v : B) v = d(rng);
        sgemm(A.data(), B.data(), C.data(), M, N, K);
        for (int64_t m = 0; m < M; ++m)
          for (int64_t j = 0; j < N; ++j) {
            float acc = 0.f;
            for (int64_t k = 0; k < K; ++k)
              acc += A[size_t(m * K + k)] * B[size_t(k * N + j)];
            assert(std::fabs(C[size_t(m * N + j)] - acc) <=
                   2e-4f * (1.f + std::fabs(acc)));
          }
      }
    }
  }
}

/* The fused epilogue: bias-per-column + relu must equal gemm followed
 * by the separate add/max passes (the op-fusion contract). */
void test_gemm_bias_act_epilogue() {
  std::mt19937 rng(11);
  std::uniform_real_distribution<float> d(-1.f, 1.f);
  const int64_t M = 13, N = 21, K = 37;
  std::vector<float> A(size_t(M * K)), B(size_t(K * N));
  std::vector<float> bias(size_t(N), 0.f);
  std::vector<float> C(size_t(M * N)), R(size_t(M * N));
  for (auto& v : A) v = d(rng);
  for (auto& v : B) v = d(rng);
  for (auto& v : bias) v = d(rng);
  gemm_bias_act<float>(A.data(), B.data(), C.data(), M, N, K, nullptr,
                       nullptr, bias.data(), nullptr, ACT_RELU);
  sgemm(A.data(), B.data(), R.data(), M, N, K);
  for (int64_t m = 0; m < M; ++m)
    for (int64_t j = 0; j < N; ++j) {
      const float want =
          std::max(0.f, R[size_t(m * N + j)] + bias[size_t(j)]);
      assert(std::fabs(C[size_t(m * N + j)] - want) <= 1e-5f);
    }
  // bias per ROW (the conv layout)
  std::vector<float> bm(size_t(M), 0.f);
  for (auto& v : bm) v = d(rng);
  gemm_bias_act<float>(A.data(), B.data(), C.data(), M, N, K, nullptr,
                       nullptr, nullptr, bm.data(), ACT_NONE);
  for (int64_t m = 0; m < M; ++m)
    for (int64_t j = 0; j < N; ++j)
      assert(std::fabs(C[size_t(m * N + j)] -
                       (R[size_t(m * N + j)] + bm[size_t(m)])) <= 1e-5f);
  // K == 0 is an EMPTY SUM: C must still be fully written (bias +
  // act of 0), never left as stale memory — the arena planner skips
  // zero-fill on the promise that every op writes its whole output
  // (code-review finding on the ISSUE 11 zero-extent guards)
  std::fill(C.begin(), C.end(), -123.f);
  gemm_bias_act<float>(A.data(), B.data(), C.data(), M, N, 0, nullptr,
                       nullptr, bias.data(), nullptr, ACT_RELU);
  for (int64_t m = 0; m < M; ++m)
    for (int64_t j = 0; j < N; ++j)
      assert(C[size_t(m * N + j)] == std::max(0.f, bias[size_t(j)]));
  std::vector<int32_t> Ci(size_t(M * N), -77);
  gemm_compute_i16(nullptr, nullptr, Ci.data(), M, N, 0);
  for (int32_t v : Ci) assert(v == 0);
}

/* WorkPool concurrency: two threads dispatching interleaved
 * parallel_for batches (two predictors serving concurrently — the r5
 * singleton race). Each thread owns a disjoint array; any cross-talk
 * between dispatches corrupts a counter. */
void test_workpool_two_thread_stress() {
  const int iters = 200;
  auto worker = [&](std::vector<int>* hits) {
    for (int it = 0; it < iters; ++it) {
      std::fill(hits->begin(), hits->end(), 0);
      parallel_for(int64_t(hits->size()), 3, [&](int64_t lo, int64_t hi) {
        for (int64_t i = lo; i < hi; ++i) (*hits)[size_t(i)]++;
      });
      for (int v : *hits) assert(v == 1);
    }
  };
  std::vector<int> h1(997, 0), h2(1501, 0);
  std::thread t1(worker, &h1), t2(worker, &h2);
  t1.join();
  t2.join();
}

/* PlanArena: disjoint lifetimes share offsets; the virtual size stays
 * at the peak, and freed space coalesces for bigger later tensors. */
void test_plan_arena_reuses_offsets() {
  ptpu::PlanArena a(64);
  const uint64_t o1 = a.Alloc(100);  // rounds to 128
  const uint64_t o2 = a.Alloc(50);
  a.Free(o1, 100);
  const uint64_t o3 = a.Alloc(100);  // must reuse o1's block
  assert(o3 == o1);
  a.Free(o2, 50);
  a.Free(o3, 100);
  const uint64_t o4 = a.Alloc(192);  // coalesced: fits in freed space
  assert(o4 == 0);
  assert(a.Size() == 192);  // 128 + 64, never grew past the peak
  // tail-aware growth: a partially-free tail extends instead of a
  // whole new block appended after it
  ptpu::PlanArena b(64);
  const uint64_t p1 = b.Alloc(64);
  b.Free(p1, 64);
  const uint64_t p2 = b.Alloc(128);  // reuses the 64-byte free tail
  assert(p2 == 0);
  assert(b.Size() == 128);
}

/* pack_b_im2col's segment emitter against the naive per-element
 * reference for strided + padded + dilated taps. */
void test_pack_b_im2col_matches_reference() {
  const int64_t ICG = 3, H = 7, W = 9, KH = 3, KW = 3;
  const int64_t sh = 2, sw = 1, ph = 1, pw = 2, dh = 1, dw = 2;
  const int64_t OH = (H + 2 * ph - dh * (KH - 1) - 1) / sh + 1;
  const int64_t OW = (W + 2 * pw - dw * (KW - 1) - 1) / sw + 1;
  const int64_t P = OH * OW, CK = ICG * KH * KW;
  std::vector<float> x(size_t(ICG * H * W));
  for (size_t k = 0; k < x.size(); ++k) x[k] = float(k) * 0.25f - 3.f;
  std::vector<float> packed(size_t(b_pack_size(CK, P)), -9.f);
  pack_b_im2col<float, float>(x.data(), ICG, H, W, KH, KW, OH, OW, sh, sw,
                              ph, pw, dh, dw, packed.data());
  for (int64_t r = 0; r < CK; ++r) {
    const int64_t ic = r / (KH * KW), kh = (r / KW) % KH, kw = r % KW;
    for (int64_t p = 0; p < P; ++p) {
      const int64_t oh = p / OW, ow = p % OW;
      const int64_t ih = oh * sh - ph + kh * dh;
      const int64_t iw = ow * sw - pw + kw * dw;
      const float want = (ih < 0 || ih >= H || iw < 0 || iw >= W)
                             ? 0.f
                             : x[size_t((ic * H + ih) * W + iw)];
      const float got =
          packed[size_t(((p / NR) * CK + r) * NR + (p % NR))];
      assert(got == want);
    }
  }
}

void test_predictor_run_stats_accumulate() {
  // hand-built one-node graph: run() must time the node, count the
  // run, and render it all in stats_json (the ABI the Python binding
  // parses); reset must zero it
  Predictor p;
  Node n;
  n.op = "Relu";
  n.inputs = {"x"};
  n.outputs = {"y"};
  p.g.nodes.push_back(n);
  p.g.output_names = {"y"};
  Tensor x;
  x.dtype = DT_F32;
  x.dims = {4};
  const std::vector<float> vals{-1.f, 2.f, -3.f, 4.f};
  x.f.assign(vals.begin(), vals.end());
  p.env["x"] = x;
  p.build_stats_index();
  p.run();
  p.env["x"] = x;
  p.run();
  assert(p.runs_ == 2);
  assert(p.op_stats_["Relu"].calls == 2);
  assert(p.op_stats_["Relu"].bytes == 2 * 4 * sizeof(float));
  assert(p.run_us_.count.load() == 2);
  const std::string j =
      ptpu_predictor_stats_json((PTPU_Predictor*)&p);
  assert(j.find("\"runs\":2") != std::string::npos);
  assert(j.find("\"Relu\"") != std::string::npos);
  assert(j.find("\"calls\":2") != std::string::npos);
  ptpu_predictor_stats_reset((PTPU_Predictor*)&p);
  assert(p.runs_ == 0 && p.op_stats_["Relu"].calls == 0);
}

// --------------------------------------------------------------- r9
// graph-construction helpers for the transformer-fusion parity tests
Tensor mk_f32(const std::vector<int64_t>& dims,
              const std::vector<float>& vals) {
  Tensor t;
  t.dtype = DT_F32;
  t.dims = dims;
  t.f.assign(vals.begin(), vals.end());
  return t;
}
Tensor mk_i64(const std::vector<int64_t>& dims,
              const std::vector<int64_t>& vals) {
  Tensor t;
  t.dtype = DT_I64;
  t.dims = dims;
  t.i.assign(vals.begin(), vals.end());
  return t;
}
Tensor mk_bool(const std::vector<int64_t>& dims,
               const std::vector<int64_t>& vals) {
  Tensor t;
  t.dtype = DT_BOOL;
  t.dims = dims;
  t.i.assign(vals.begin(), vals.end());
  return t;
}
void add_init(Predictor* p, const std::string& name, Tensor t) {
  p->env[name] = t;
  p->g.initializers[name] = std::move(t);
}
Node mk_node(const std::string& op, std::vector<std::string> ins,
             std::vector<std::string> outs) {
  Node n;
  n.op = op;
  n.inputs = std::move(ins);
  n.outputs = std::move(outs);
  return n;
}
void set_ints(Node* n, const char* name, std::vector<int64_t> v) {
  Attr a;
  a.ints = std::move(v);
  n->attrs[name] = a;
}
void set_ival(Node* n, const char* name, int64_t v) {
  Attr a;
  a.ival = v;
  n->attrs[name] = a;
}

/* Replicates the exporter's attention lowering byte for byte (the
 * pattern fuse_attention matches): transposes + rank-3 reshapes +
 * batched MatMuls + scalar scale (+ optional const mask Where) + the
 * ReduceMax/Max/Sub/Exp/ReduceSum/Div softmax + output transpose +
 * flatten. `sm_axis` parametrizes the softmax axis so a near-miss
 * (axis != last) proves the matcher refuses to fuse it. */
void build_attention_graph(Predictor* p, int64_t b, int64_t s, int64_t h,
                           int64_t d, bool masked, int64_t sm_axis) {
  Graph& g = p->g;
  g.input_names = {"q", "k", "v"};
  for (const auto& nm : g.input_names) {
    g.input_dims[nm] = {b, s, h, d};
    g.input_dtypes[nm] = DT_F32;
  }
  g.output_names = {"out"};
  add_init(p, "sh_q3", mk_i64({3}, {b * h, s, d}));
  add_init(p, "sh_k3", mk_i64({3}, {b * h, d, s}));
  add_init(p, "sh_s4", mk_i64({4}, {b, h, s, s}));
  add_init(p, "sh_keep", mk_i64({4}, {b, h, s, 1}));
  add_init(p, "sh_p3", mk_i64({3}, {b * h, s, s}));
  add_init(p, "sh_o4", mk_i64({4}, {b, h, s, d}));
  add_init(p, "sh_flat", mk_i64({3}, {b, s, h * d}));
  add_init(p, "scale", mk_f32({}, {0.37f}));
  add_init(p, "ninf", mk_f32({}, {-std::numeric_limits<float>::infinity()}));
  add_init(p, "axes_last", mk_i64({1}, {3}));
  if (masked) {
    // lower-triangular causal mask + a folded -inf else tensor, the
    // shapes the exporter's folded Where carries
    std::vector<int64_t> mv(size_t(s * s));
    for (int64_t i = 0; i < s; ++i)
      for (int64_t j = 0; j < s; ++j) mv[size_t(i * s + j)] = j <= i;
    add_init(p, "maskc", mk_bool({1, 1, s, s}, mv));
    add_init(p, "negc",
             mk_f32({1, 1, 1, 1},
                    {-std::numeric_limits<float>::infinity()}));
  }
  std::vector<Node> ns;
  Node t1 = mk_node("Transpose", {"q"}, {"qt"});
  set_ints(&t1, "perm", {0, 2, 1, 3});
  ns.push_back(t1);
  Node t2 = mk_node("Transpose", {"qt"}, {"qt2"});
  set_ints(&t2, "perm", {0, 1, 2, 3});
  ns.push_back(t2);
  ns.push_back(mk_node("Reshape", {"qt2", "sh_q3"}, {"q3"}));
  Node t3 = mk_node("Transpose", {"k"}, {"kt"});
  set_ints(&t3, "perm", {0, 2, 1, 3});
  ns.push_back(t3);
  Node t4 = mk_node("Transpose", {"kt"}, {"kt2"});
  set_ints(&t4, "perm", {0, 1, 3, 2});
  ns.push_back(t4);
  ns.push_back(mk_node("Reshape", {"kt2", "sh_k3"}, {"k3"}));
  ns.push_back(mk_node("MatMul", {"q3", "k3"}, {"mm1"}));
  ns.push_back(mk_node("Reshape", {"mm1", "sh_s4"}, {"s4"}));
  ns.push_back(mk_node("Mul", {"s4", "scale"}, {"sc"}));
  const char* scores = "sc";
  if (masked) {
    ns.push_back(mk_node("Where", {"maskc", "sc", "negc"}, {"scm"}));
    scores = "scm";
  }
  Node rm = mk_node("ReduceMax", {scores}, {"rm"});
  set_ints(&rm, "axes", {sm_axis});
  set_ival(&rm, "keepdims", 0);
  ns.push_back(rm);
  ns.push_back(mk_node("Max", {"ninf", "rm"}, {"mx"}));
  ns.push_back(mk_node("Reshape", {"mx", "sh_keep"}, {"mxr"}));
  ns.push_back(mk_node("Sub", {scores, "mxr"}, {"sub"}));
  ns.push_back(mk_node("Exp", {"sub"}, {"ex"}));
  Node rs = mk_node("ReduceSum", {"ex", "axes_last"}, {"rs"});
  set_ival(&rs, "keepdims", 0);
  ns.push_back(rs);
  ns.push_back(mk_node("Reshape", {"rs", "sh_keep"}, {"rsr"}));
  ns.push_back(mk_node("Div", {"ex", "rsr"}, {"pr"}));
  Node t5 = mk_node("Transpose", {"pr"}, {"prt"});
  set_ints(&t5, "perm", {0, 1, 2, 3});
  ns.push_back(t5);
  ns.push_back(mk_node("Reshape", {"prt", "sh_p3"}, {"pr3"}));
  Node t6 = mk_node("Transpose", {"v"}, {"vt"});
  set_ints(&t6, "perm", {0, 2, 1, 3});
  ns.push_back(t6);
  Node t7 = mk_node("Transpose", {"vt"}, {"vt2"});
  set_ints(&t7, "perm", {0, 1, 2, 3});
  ns.push_back(t7);
  ns.push_back(mk_node("Reshape", {"vt2", "sh_q3"}, {"v3"}));
  ns.push_back(mk_node("MatMul", {"pr3", "v3"}, {"mm2"}));
  ns.push_back(mk_node("Reshape", {"mm2", "sh_o4"}, {"o4"}));
  Node t8 = mk_node("Transpose", {"o4"}, {"ot"});
  set_ints(&t8, "perm", {0, 2, 1, 3});
  ns.push_back(t8);
  ns.push_back(mk_node("Reshape", {"ot", "sh_flat"}, {"out"}));
  g.nodes = std::move(ns);
}

int count_op(const Predictor& p, const char* op) {
  int c = 0;
  for (const auto& n : p.g.nodes)
    if (n.op == op) ++c;
  return c;
}

void run_with_qkv(Predictor* p, const std::vector<float>& q,
                  const std::vector<float>& k,
                  const std::vector<float>& v,
                  const std::vector<int64_t>& dims) {
  Tensor tq = mk_f32(dims, q), tk = mk_f32(dims, k), tv = mk_f32(dims, v);
  p->env["q"] = tq;
  p->env["k"] = tk;
  p->env["v"] = tv;
  p->build_stats_index();
  p->run();
}

void test_attention_fusion_parity() {
  // odd seq, masked and unmasked, plus the near-miss axis control
  for (int masked = 0; masked < 2; ++masked) {
    const int64_t b = 2, s = 5, h = 2, d = 3;
    std::mt19937 rng(7 + masked);
    std::uniform_real_distribution<float> dist(-1.f, 1.f);
    std::vector<float> q(size_t(b * s * h * d)), k(q.size()), v(q.size());
    for (auto& x : q) x = dist(rng);
    for (auto& x : k) x = dist(rng);
    for (auto& x : v) x = dist(rng);

    Predictor ref;
    build_attention_graph(&ref, b, s, h, d, masked != 0, 3);
    run_with_qkv(&ref, q, k, v, {b, s, h, d});

    Predictor fp;
    build_attention_graph(&fp, b, s, h, d, masked != 0, 3);
    std::map<std::string, std::vector<int64_t>> shp;
    std::map<std::string, int> dty;
    assert(fp.dry_run_shapes(&shp, &dty));
    fp.fuse_attention(shp);
    assert(count_op(fp, "PtpuAttention") == 1);
    assert(count_op(fp, "MatMul") == 0 && count_op(fp, "Exp") == 0);
    run_with_qkv(&fp, q, k, v, {b, s, h, d});

    assert(ref.outputs.size() == 1 && fp.outputs.size() == 1);
    assert(ref.outputs[0].dims == fp.outputs[0].dims);
    for (int64_t i = 0; i < ref.outputs[0].numel(); ++i) {
      const float a = ref.outputs[0].f[size_t(i)];
      const float bv = fp.outputs[0].f[size_t(i)];
      assert(std::fabs(a - bv) <= 1e-5f * (1.f + std::fabs(a)));
    }
  }
  // NEAR-MISS control: softmax over axis 2 (not last) must NOT fuse
  {
    Predictor nf;
    build_attention_graph(&nf, 2, 4, 2, 3, false, 2);
    std::map<std::string, std::vector<int64_t>> shp;
    std::map<std::string, int> dty;
    // the axis-2 ReduceMax makes Sub/Div shapes inconsistent with the
    // keepdim reshape targets, so the dry run itself may throw OR the
    // matcher must refuse — either way: no PtpuAttention node
    if (nf.dry_run_shapes(&shp, &dty)) nf.fuse_attention(shp);
    assert(count_op(nf, "PtpuAttention") == 0);
  }
}

void test_layernorm_fusion_parity() {
  const int64_t b = 2, s = 3, D = 4;
  Predictor ref, fp;
  for (Predictor* p : {&ref, &fp}) {
    Graph& g = p->g;
    g.input_names = {"x"};
    g.input_dims["x"] = {b, s, D};
    g.input_dtypes["x"] = DT_F32;
    g.output_names = {"out"};
    add_init(p, "axes", mk_i64({1}, {2}));
    add_init(p, "sh_keep", mk_i64({3}, {b, s, 1}));
    add_init(p, "Dc", mk_f32({}, {float(D)}));
    add_init(p, "eps", mk_f32({}, {1e-5f}));
    add_init(p, "negone", mk_f32({}, {-1.f}));
    add_init(p, "gamma", mk_f32({1, 1, D}, {1.5f, 0.5f, -2.f, 1.f}));
    add_init(p, "beta", mk_f32({1, 1, D}, {0.1f, -0.2f, 0.3f, 0.f}));
    add_init(p, "condc", mk_bool({b, s, 1}, std::vector<int64_t>(
                                                size_t(b * s), 1)));
    add_init(p, "altc",
             mk_f32({b, s, 1}, std::vector<float>(size_t(b * s),
                                                  std::nanf(""))));
    std::vector<Node> ns;
    Node r1 = mk_node("ReduceSum", {"x", "axes"}, {"s1"});
    set_ival(&r1, "keepdims", 0);
    ns.push_back(r1);
    ns.push_back(mk_node("Reshape", {"s1", "sh_keep"}, {"r1"}));
    ns.push_back(mk_node("Div", {"r1", "Dc"}, {"meanA"}));
    Node r2 = mk_node("ReduceSum", {"x", "axes"}, {"s2"});
    set_ival(&r2, "keepdims", 0);
    ns.push_back(r2);
    ns.push_back(mk_node("Reshape", {"s2", "sh_keep"}, {"r2"}));
    ns.push_back(mk_node("Div", {"r2", "Dc"}, {"meanB"}));
    ns.push_back(mk_node("Sub", {"x", "meanB"}, {"c2"}));
    ns.push_back(mk_node("Mul", {"c2", "c2"}, {"sq"}));
    Node r3 = mk_node("ReduceSum", {"sq", "axes"}, {"s3"});
    set_ival(&r3, "keepdims", 0);
    ns.push_back(r3);
    ns.push_back(mk_node("Reshape", {"s3", "sh_keep"}, {"r3"}));
    ns.push_back(mk_node("Div", {"r3", "Dc"}, {"var"}));
    ns.push_back(mk_node("Where", {"condc", "var", "altc"}, {"varg"}));
    ns.push_back(mk_node("Add", {"varg", "eps"}, {"ve"}));
    ns.push_back(mk_node("Sqrt", {"ve"}, {"sqv"}));
    ns.push_back(mk_node("Pow", {"sqv", "negone"}, {"rstd"}));
    ns.push_back(mk_node("Sub", {"x", "meanA"}, {"c1"}));
    ns.push_back(mk_node("Mul", {"c1", "rstd"}, {"m1"}));
    ns.push_back(mk_node("Mul", {"m1", "gamma"}, {"m2"}));
    ns.push_back(mk_node("Add", {"m2", "beta"}, {"out"}));
    g.nodes = std::move(ns);
  }
  std::mt19937 rng(11);
  std::uniform_real_distribution<float> dist(-2.f, 2.f);
  std::vector<float> x(size_t(b * s * D), 0.f);
  for (auto& v2 : x) v2 = dist(rng);
  const auto run_x = [&](Predictor* p) {
    p->env["x"] = mk_f32({b, s, D}, x);
    p->build_stats_index();
    p->run();
  };
  run_x(&ref);
  std::map<std::string, std::vector<int64_t>> shp;
  std::map<std::string, int> dty;
  assert(fp.dry_run_shapes(&shp, &dty));
  fp.fuse_layernorm(shp);
  assert(count_op(fp, "PtpuLayerNorm") == 1);
  assert(count_op(fp, "Sqrt") == 0 && count_op(fp, "ReduceSum") == 0);
  run_x(&fp);
  for (int64_t i = 0; i < ref.outputs[0].numel(); ++i) {
    const float a = ref.outputs[0].f[size_t(i)];
    const float bv = fp.outputs[0].f[size_t(i)];
    assert(std::fabs(a - bv) <= 1e-5f * (1.f + std::fabs(a)));
  }
}

void test_gelu_fusion_bitwise() {
  const int64_t n = 2 * 7;
  Predictor ref, fp;
  for (Predictor* p : {&ref, &fp}) {
    Graph& g = p->g;
    g.input_names = {"x"};
    g.input_dims["x"] = {2, 7};
    g.input_dtypes["x"] = DT_F32;
    g.output_names = {"out"};
    add_init(p, "three", mk_f32({}, {3.f}));
    add_init(p, "c1", mk_f32({}, {0.044715f}));
    add_init(p, "c2", mk_f32({}, {0.7978846f}));
    add_init(p, "one", mk_f32({}, {1.f}));
    add_init(p, "half", mk_f32({}, {0.5f}));
    std::vector<Node> ns;
    ns.push_back(mk_node("Pow", {"x", "three"}, {"p3"}));
    ns.push_back(mk_node("Mul", {"c1", "p3"}, {"m1"}));
    ns.push_back(mk_node("Add", {"x", "m1"}, {"a1"}));
    ns.push_back(mk_node("Mul", {"c2", "a1"}, {"m2"}));
    ns.push_back(mk_node("Tanh", {"m2"}, {"t"}));
    ns.push_back(mk_node("Add", {"one", "t"}, {"a2"}));
    ns.push_back(mk_node("Mul", {"half", "a2"}, {"m3"}));
    ns.push_back(mk_node("Mul", {"x", "m3"}, {"out"}));
    g.nodes = std::move(ns);
  }
  std::mt19937 rng(13);
  std::uniform_real_distribution<float> dist(-3.f, 3.f);
  std::vector<float> x(size_t(n), 0.f);
  for (auto& v2 : x) v2 = dist(rng);
  const auto run_x = [&](Predictor* p) {
    p->env["x"] = mk_f32({2, 7}, x);
    p->build_stats_index();
    p->run();
  };
  run_x(&ref);
  fp.fuse_gelu();
  assert(count_op(fp, "PtpuGelu") == 1 && fp.g.nodes.size() == 1);
  run_x(&fp);
  for (int64_t i = 0; i < n; ++i)   // same float ops, same order
    assert(ref.outputs[0].f[size_t(i)] == fp.outputs[0].f[size_t(i)]);
}

void test_gemm_i16_pair_path_exact() {
  // the VNNI pair-packed path (vpdpwssd where cpuid allows, generic
  // pair kernel otherwise) must match the scalar reference EXACTLY —
  // integer adds are associative, so any reordering is still ==
  std::mt19937 rng(17);
  std::uniform_int_distribution<int> dist(-128, 127);
  for (const auto& mnk : {std::array<int64_t, 3>{9, 13, 21},
                          std::array<int64_t, 3>{16, 16, 32},
                          std::array<int64_t, 3>{7, 33, 5}}) {
    const int64_t M = mnk[0], N = mnk[1], K = mnk[2];
    std::vector<int64_t> A(size_t(M * K)), B(size_t(K * N));
    for (auto& v : A) v = dist(rng);
    for (auto& v : B) v = dist(rng);
    std::vector<int32_t> C(size_t(M * N), 0);
    gemm_i16(A.data(), B.data(), C.data(), M, N, K, nullptr);
    for (int64_t m = 0; m < M; ++m)
      for (int64_t j = 0; j < N; ++j) {
        int64_t acc = 0;
        for (int64_t k = 0; k < K; ++k)
          acc += A[size_t(m * K + k)] * B[size_t(k * N + j)];
        assert(C[size_t(m * N + j)] == acc);
      }
  }
  std::printf("  gemm_i16 exact (vnni=%d, isa=%d)\n", int(isa_vnni()),
              isa_level());
}

}  // namespace

int main() {
  test_sgemm_matches_naive();
  test_sgemm_propagates_nan_through_zero();
  test_igemm_exact();
  test_int8_exact_bounds();
  test_bcast_walk_matches_divmod();
  test_check_dims_rejects();
  test_parallel_for_covers_range();
  test_packed_gemm_fringe_sweep();
  test_gemm_bias_act_epilogue();
  test_workpool_two_thread_stress();
  test_plan_arena_reuses_offsets();
  test_pack_b_im2col_matches_reference();
  test_predictor_run_stats_accumulate();
  test_attention_fusion_parity();
  test_layernorm_fusion_parity();
  test_gelu_fusion_bitwise();
  test_gemm_i16_pair_path_exact();
  std::printf("ptpu_selftest: all native unit tests passed\n");
  return 0;
}
