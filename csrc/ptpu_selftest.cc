// Native unit tests for the predictor TU internals — the cc_test
// analogue (reference: gtest cc_test targets per CMakeLists, e.g.
// `paddle/fluid/framework/data_type_test.cc`). Plain asserts, no test
// framework dependency; exit 0 = pass. Includes the predictor TU
// directly so the anonymous-namespace kernels (sgemm/igemm/bcast_walk/
// int8_exact/check_dims) are testable without widening their linkage.
//
// Build + run: make selftest (csrc/Makefile); wrapped by
// tests/test_native_selftest.py.
#include "ptpu_predictor.cc"

// asserts ARE the test — never compile them out, even under a
// release-style CXXFLAGS override carrying -DNDEBUG
#undef NDEBUG
#include <cassert>
#include <cstdio>
#include <fstream>
#include <iterator>
#include <limits>
#include <random>

namespace {

void test_sgemm_matches_naive() {
  std::mt19937 rng(0);
  std::uniform_real_distribution<float> d(-1.f, 1.f);
  const int64_t M = 17, N = 33, K = 29;
  std::vector<float> A(M * K), B(K * N), C(M * N), ref(M * N, 0.f);
  for (auto& v : A) v = d(rng);
  for (auto& v : B) v = d(rng);
  sgemm(A.data(), B.data(), C.data(), M, N, K);
  for (int64_t m = 0; m < M; ++m)
    for (int64_t j = 0; j < N; ++j) {
      float acc = 0.f;
      for (int64_t k = 0; k < K; ++k) acc += A[m * K + k] * B[k * N + j];
      ref[m * N + j] = acc;
    }
  for (int64_t i = 0; i < M * N; ++i)
    assert(std::fabs(C[i] - ref[i]) <= 1e-4f * (1.f + std::fabs(ref[i])));
}

void test_sgemm_propagates_nan_through_zero() {
  // IEEE: 0 * NaN must stay NaN (the zero-skip regression guard)
  const float nan = std::nanf("");
  std::vector<float> A{0.f, 1.f}, B{nan, 2.f}, C(1);
  sgemm(A.data(), B.data(), C.data(), 1, 1, 2);
  assert(std::isnan(C[0]));
}

void test_igemm_exact() {
  std::mt19937 rng(1);
  std::uniform_int_distribution<int> d(-128, 127);
  const int64_t M = 9, N = 13, K = 21;
  std::vector<int32_t> A(M * K), B(K * N), C(M * N);
  for (auto& v : A) v = d(rng);
  for (auto& v : B) v = d(rng);
  igemm(A.data(), B.data(), C.data(), M, N, K);
  for (int64_t m = 0; m < M; ++m)
    for (int64_t j = 0; j < N; ++j) {
      int64_t acc = 0;
      for (int64_t k = 0; k < K; ++k)
        acc += int64_t(A[m * K + k]) * B[k * N + j];
      assert(C[m * N + j] == acc);
    }
}

void test_int8_exact_bounds() {
  std::vector<int64_t> ok{-128, 127, 0}, bad{-129}, big{128};
  const int64_t kmax = (int64_t(1) << 31) / (128 * 128);
  assert(int8_exact(ok, ok, kmax - 1));
  assert(!int8_exact(ok, ok, kmax));      // strict: 2^31 would overflow
  assert(!int8_exact(bad, ok, 4));
  assert(!int8_exact(ok, big, 4));
}

void test_bcast_walk_matches_divmod() {
  // [2,3,4] (x) [3,1] -> [2,3,4]; compare odometer against bcast_index
  std::vector<int64_t> od{2, 3, 4}, ad{2, 3, 4}, bd{3, 1};
  bcast_walk(od, ad, bd, [&](int64_t k, int64_t ai, int64_t bi) {
    assert(ai == bcast_index(k, od, ad));
    assert(bi == bcast_index(k, od, bd));
  });
  // scalar operand
  std::vector<int64_t> sd{};
  bcast_walk(od, ad, sd, [&](int64_t, int64_t, int64_t bi) {
    assert(bi == 0);
  });
}

void test_check_dims_rejects() {
  int64_t neg[2] = {2, -1};
  bool threw = false;
  try {
    check_dims(neg, 2);
  } catch (const std::exception&) {
    threw = true;
  }
  assert(threw);
  int64_t huge[2] = {3037000500LL, 3037000500LL};
  threw = false;
  try {
    check_dims(huge, 2);
  } catch (const std::exception&) {
    threw = true;
  }
  assert(threw);
  check_dims(nullptr, 0);  // 0-d scalar is legal
}

void test_parallel_for_covers_range() {
  std::vector<int> hit(1000, 0);
  parallel_for(1000, 7, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) hit[size_t(i)]++;
  });
  for (int v : hit) assert(v == 1);
}

/* Fringe sweep for the packed cache-blocked GEMM: every (M % MR,
 * N % NR) combination plus K crossing a KC boundary must match the
 * naive triple loop — the panel zero-padding and partial-tile
 * load/store paths are all exercised. */
void test_packed_gemm_fringe_sweep() {
  std::mt19937 rng(7);
  std::uniform_real_distribution<float> d(-1.f, 1.f);
  for (int64_t M : {1, 5, 6, 7, 13}) {
    for (int64_t N : {1, 15, 16, 17, 33}) {
      for (int64_t K : {1, 31, 321}) {  // 321 crosses the KC=320 block
        std::vector<float> A(size_t(M * K)), B(size_t(K * N));
        std::vector<float> C(size_t(M * N), -7.f);
        for (auto& v : A) v = d(rng);
        for (auto& v : B) v = d(rng);
        sgemm(A.data(), B.data(), C.data(), M, N, K);
        for (int64_t m = 0; m < M; ++m)
          for (int64_t j = 0; j < N; ++j) {
            float acc = 0.f;
            for (int64_t k = 0; k < K; ++k)
              acc += A[size_t(m * K + k)] * B[size_t(k * N + j)];
            assert(std::fabs(C[size_t(m * N + j)] - acc) <=
                   2e-4f * (1.f + std::fabs(acc)));
          }
      }
    }
  }
}

/* The fused epilogue: bias-per-column + relu must equal gemm followed
 * by the separate add/max passes (the op-fusion contract). */
void test_gemm_bias_act_epilogue() {
  std::mt19937 rng(11);
  std::uniform_real_distribution<float> d(-1.f, 1.f);
  const int64_t M = 13, N = 21, K = 37;
  std::vector<float> A(size_t(M * K)), B(size_t(K * N));
  std::vector<float> bias(size_t(N), 0.f);
  std::vector<float> C(size_t(M * N)), R(size_t(M * N));
  for (auto& v : A) v = d(rng);
  for (auto& v : B) v = d(rng);
  for (auto& v : bias) v = d(rng);
  gemm_bias_act<float>(A.data(), B.data(), C.data(), M, N, K, nullptr,
                       nullptr, bias.data(), nullptr, ACT_RELU);
  sgemm(A.data(), B.data(), R.data(), M, N, K);
  for (int64_t m = 0; m < M; ++m)
    for (int64_t j = 0; j < N; ++j) {
      const float want =
          std::max(0.f, R[size_t(m * N + j)] + bias[size_t(j)]);
      assert(std::fabs(C[size_t(m * N + j)] - want) <= 1e-5f);
    }
  // bias per ROW (the conv layout)
  std::vector<float> bm(size_t(M), 0.f);
  for (auto& v : bm) v = d(rng);
  gemm_bias_act<float>(A.data(), B.data(), C.data(), M, N, K, nullptr,
                       nullptr, nullptr, bm.data(), ACT_NONE);
  for (int64_t m = 0; m < M; ++m)
    for (int64_t j = 0; j < N; ++j)
      assert(std::fabs(C[size_t(m * N + j)] -
                       (R[size_t(m * N + j)] + bm[size_t(m)])) <= 1e-5f);
  // K == 0 is an EMPTY SUM: C must still be fully written (bias +
  // act of 0), never left as stale memory — the arena planner skips
  // zero-fill on the promise that every op writes its whole output
  // (code-review finding on the ISSUE 11 zero-extent guards)
  std::fill(C.begin(), C.end(), -123.f);
  gemm_bias_act<float>(A.data(), B.data(), C.data(), M, N, 0, nullptr,
                       nullptr, bias.data(), nullptr, ACT_RELU);
  for (int64_t m = 0; m < M; ++m)
    for (int64_t j = 0; j < N; ++j)
      assert(C[size_t(m * N + j)] == std::max(0.f, bias[size_t(j)]));
  std::vector<int32_t> Ci(size_t(M * N), -77);
  gemm_compute_i16(nullptr, nullptr, Ci.data(), M, N, 0);
  for (int32_t v : Ci) assert(v == 0);
}

/* WorkPool concurrency: two threads dispatching interleaved
 * parallel_for batches (two predictors serving concurrently — the r5
 * singleton race). Each thread owns a disjoint array; any cross-talk
 * between dispatches corrupts a counter. */
void test_workpool_two_thread_stress() {
  const int iters = 200;
  auto worker = [&](std::vector<int>* hits) {
    for (int it = 0; it < iters; ++it) {
      std::fill(hits->begin(), hits->end(), 0);
      parallel_for(int64_t(hits->size()), 3, [&](int64_t lo, int64_t hi) {
        for (int64_t i = lo; i < hi; ++i) (*hits)[size_t(i)]++;
      });
      for (int v : *hits) assert(v == 1);
    }
  };
  std::vector<int> h1(997, 0), h2(1501, 0);
  std::thread t1(worker, &h1), t2(worker, &h2);
  t1.join();
  t2.join();
}

/* PlanArena: disjoint lifetimes share offsets; the virtual size stays
 * at the peak, and freed space coalesces for bigger later tensors. */
void test_plan_arena_reuses_offsets() {
  ptpu::PlanArena a(64);
  const uint64_t o1 = a.Alloc(100);  // rounds to 128
  const uint64_t o2 = a.Alloc(50);
  a.Free(o1, 100);
  const uint64_t o3 = a.Alloc(100);  // must reuse o1's block
  assert(o3 == o1);
  a.Free(o2, 50);
  a.Free(o3, 100);
  const uint64_t o4 = a.Alloc(192);  // coalesced: fits in freed space
  assert(o4 == 0);
  assert(a.Size() == 192);  // 128 + 64, never grew past the peak
  // tail-aware growth: a partially-free tail extends instead of a
  // whole new block appended after it
  ptpu::PlanArena b(64);
  const uint64_t p1 = b.Alloc(64);
  b.Free(p1, 64);
  const uint64_t p2 = b.Alloc(128);  // reuses the 64-byte free tail
  assert(p2 == 0);
  assert(b.Size() == 128);
}

/* pack_b_im2col's segment emitter against the naive per-element
 * reference for strided + padded + dilated taps. */
void test_pack_b_im2col_matches_reference() {
  const int64_t ICG = 3, H = 7, W = 9, KH = 3, KW = 3;
  const int64_t sh = 2, sw = 1, ph = 1, pw = 2, dh = 1, dw = 2;
  const int64_t OH = (H + 2 * ph - dh * (KH - 1) - 1) / sh + 1;
  const int64_t OW = (W + 2 * pw - dw * (KW - 1) - 1) / sw + 1;
  const int64_t P = OH * OW, CK = ICG * KH * KW;
  std::vector<float> x(size_t(ICG * H * W));
  for (size_t k = 0; k < x.size(); ++k) x[k] = float(k) * 0.25f - 3.f;
  std::vector<float> packed(size_t(b_pack_size(CK, P)), -9.f);
  pack_b_im2col<float, float>(x.data(), ICG, H, W, KH, KW, OH, OW, sh, sw,
                              ph, pw, dh, dw, packed.data());
  for (int64_t r = 0; r < CK; ++r) {
    const int64_t ic = r / (KH * KW), kh = (r / KW) % KH, kw = r % KW;
    for (int64_t p = 0; p < P; ++p) {
      const int64_t oh = p / OW, ow = p % OW;
      const int64_t ih = oh * sh - ph + kh * dh;
      const int64_t iw = ow * sw - pw + kw * dw;
      const float want = (ih < 0 || ih >= H || iw < 0 || iw >= W)
                             ? 0.f
                             : x[size_t((ic * H + ih) * W + iw)];
      const float got =
          packed[size_t(((p / NR) * CK + r) * NR + (p % NR))];
      assert(got == want);
    }
  }
}

void test_predictor_run_stats_accumulate() {
  // hand-built one-node graph: run() must time the node, count the
  // run, and render it all in stats_json (the ABI the Python binding
  // parses); reset must zero it
  Predictor p;
  Node n;
  n.op = "Relu";
  n.inputs = {"x"};
  n.outputs = {"y"};
  p.g.nodes.push_back(n);
  p.g.output_names = {"y"};
  Tensor x;
  x.dtype = DT_F32;
  x.dims = {4};
  const std::vector<float> vals{-1.f, 2.f, -3.f, 4.f};
  x.f.assign(vals.begin(), vals.end());
  p.env["x"] = x;
  p.build_stats_index();
  p.run();
  p.env["x"] = x;
  p.run();
  assert(p.runs_ == 2);
  assert(p.op_stats_["Relu"].calls == 2);
  assert(p.op_stats_["Relu"].bytes == 2 * 4 * sizeof(float));
  assert(p.run_us_.count.load() == 2);
  const std::string j =
      ptpu_predictor_stats_json((PTPU_Predictor*)&p);
  assert(j.find("\"runs\":2") != std::string::npos);
  assert(j.find("\"Relu\"") != std::string::npos);
  assert(j.find("\"calls\":2") != std::string::npos);
  ptpu_predictor_stats_reset((PTPU_Predictor*)&p);
  assert(p.runs_ == 0 && p.op_stats_["Relu"].calls == 0);
}

// --------------------------------------------------------------- r9
// graph-construction helpers for the transformer-fusion parity tests
Tensor mk_f32(const std::vector<int64_t>& dims,
              const std::vector<float>& vals) {
  Tensor t;
  t.dtype = DT_F32;
  t.dims = dims;
  t.f.assign(vals.begin(), vals.end());
  return t;
}
Tensor mk_i64(const std::vector<int64_t>& dims,
              const std::vector<int64_t>& vals) {
  Tensor t;
  t.dtype = DT_I64;
  t.dims = dims;
  t.i.assign(vals.begin(), vals.end());
  return t;
}
Tensor mk_bool(const std::vector<int64_t>& dims,
               const std::vector<int64_t>& vals) {
  Tensor t;
  t.dtype = DT_BOOL;
  t.dims = dims;
  t.i.assign(vals.begin(), vals.end());
  return t;
}
void add_init(Predictor* p, const std::string& name, Tensor t) {
  p->env[name] = t;
  p->g.initializers[name] = std::move(t);
}
Node mk_node(const std::string& op, std::vector<std::string> ins,
             std::vector<std::string> outs) {
  Node n;
  n.op = op;
  n.inputs = std::move(ins);
  n.outputs = std::move(outs);
  return n;
}
void set_ints(Node* n, const char* name, std::vector<int64_t> v) {
  Attr a;
  a.ints = std::move(v);
  n->attrs[name] = a;
}
void set_ival(Node* n, const char* name, int64_t v) {
  Attr a;
  a.ival = v;
  n->attrs[name] = a;
}

/* Replicates the exporter's attention lowering byte for byte (the
 * pattern fuse_attention matches): transposes + rank-3 reshapes +
 * batched MatMuls + scalar scale (+ optional const mask Where) + the
 * ReduceMax/Max/Sub/Exp/ReduceSum/Div softmax + output transpose +
 * flatten. `sm_axis` parametrizes the softmax axis so a near-miss
 * (axis != last) proves the matcher refuses to fuse it. */
void build_attention_graph(Predictor* p, int64_t b, int64_t s, int64_t h,
                           int64_t d, bool masked, int64_t sm_axis) {
  Graph& g = p->g;
  g.input_names = {"q", "k", "v"};
  for (const auto& nm : g.input_names) {
    g.input_dims[nm] = {b, s, h, d};
    g.input_dtypes[nm] = DT_F32;
  }
  g.output_names = {"out"};
  add_init(p, "sh_q3", mk_i64({3}, {b * h, s, d}));
  add_init(p, "sh_k3", mk_i64({3}, {b * h, d, s}));
  add_init(p, "sh_s4", mk_i64({4}, {b, h, s, s}));
  add_init(p, "sh_keep", mk_i64({4}, {b, h, s, 1}));
  add_init(p, "sh_p3", mk_i64({3}, {b * h, s, s}));
  add_init(p, "sh_o4", mk_i64({4}, {b, h, s, d}));
  add_init(p, "sh_flat", mk_i64({3}, {b, s, h * d}));
  add_init(p, "scale", mk_f32({}, {0.37f}));
  add_init(p, "ninf", mk_f32({}, {-std::numeric_limits<float>::infinity()}));
  add_init(p, "axes_last", mk_i64({1}, {3}));
  if (masked) {
    // lower-triangular causal mask + a folded -inf else tensor, the
    // shapes the exporter's folded Where carries
    std::vector<int64_t> mv(size_t(s * s));
    for (int64_t i = 0; i < s; ++i)
      for (int64_t j = 0; j < s; ++j) mv[size_t(i * s + j)] = j <= i;
    add_init(p, "maskc", mk_bool({1, 1, s, s}, mv));
    add_init(p, "negc",
             mk_f32({1, 1, 1, 1},
                    {-std::numeric_limits<float>::infinity()}));
  }
  std::vector<Node> ns;
  Node t1 = mk_node("Transpose", {"q"}, {"qt"});
  set_ints(&t1, "perm", {0, 2, 1, 3});
  ns.push_back(t1);
  Node t2 = mk_node("Transpose", {"qt"}, {"qt2"});
  set_ints(&t2, "perm", {0, 1, 2, 3});
  ns.push_back(t2);
  ns.push_back(mk_node("Reshape", {"qt2", "sh_q3"}, {"q3"}));
  Node t3 = mk_node("Transpose", {"k"}, {"kt"});
  set_ints(&t3, "perm", {0, 2, 1, 3});
  ns.push_back(t3);
  Node t4 = mk_node("Transpose", {"kt"}, {"kt2"});
  set_ints(&t4, "perm", {0, 1, 3, 2});
  ns.push_back(t4);
  ns.push_back(mk_node("Reshape", {"kt2", "sh_k3"}, {"k3"}));
  ns.push_back(mk_node("MatMul", {"q3", "k3"}, {"mm1"}));
  ns.push_back(mk_node("Reshape", {"mm1", "sh_s4"}, {"s4"}));
  ns.push_back(mk_node("Mul", {"s4", "scale"}, {"sc"}));
  const char* scores = "sc";
  if (masked) {
    ns.push_back(mk_node("Where", {"maskc", "sc", "negc"}, {"scm"}));
    scores = "scm";
  }
  Node rm = mk_node("ReduceMax", {scores}, {"rm"});
  set_ints(&rm, "axes", {sm_axis});
  set_ival(&rm, "keepdims", 0);
  ns.push_back(rm);
  ns.push_back(mk_node("Max", {"ninf", "rm"}, {"mx"}));
  ns.push_back(mk_node("Reshape", {"mx", "sh_keep"}, {"mxr"}));
  ns.push_back(mk_node("Sub", {scores, "mxr"}, {"sub"}));
  ns.push_back(mk_node("Exp", {"sub"}, {"ex"}));
  Node rs = mk_node("ReduceSum", {"ex", "axes_last"}, {"rs"});
  set_ival(&rs, "keepdims", 0);
  ns.push_back(rs);
  ns.push_back(mk_node("Reshape", {"rs", "sh_keep"}, {"rsr"}));
  ns.push_back(mk_node("Div", {"ex", "rsr"}, {"pr"}));
  Node t5 = mk_node("Transpose", {"pr"}, {"prt"});
  set_ints(&t5, "perm", {0, 1, 2, 3});
  ns.push_back(t5);
  ns.push_back(mk_node("Reshape", {"prt", "sh_p3"}, {"pr3"}));
  Node t6 = mk_node("Transpose", {"v"}, {"vt"});
  set_ints(&t6, "perm", {0, 2, 1, 3});
  ns.push_back(t6);
  Node t7 = mk_node("Transpose", {"vt"}, {"vt2"});
  set_ints(&t7, "perm", {0, 1, 2, 3});
  ns.push_back(t7);
  ns.push_back(mk_node("Reshape", {"vt2", "sh_q3"}, {"v3"}));
  ns.push_back(mk_node("MatMul", {"pr3", "v3"}, {"mm2"}));
  ns.push_back(mk_node("Reshape", {"mm2", "sh_o4"}, {"o4"}));
  Node t8 = mk_node("Transpose", {"o4"}, {"ot"});
  set_ints(&t8, "perm", {0, 2, 1, 3});
  ns.push_back(t8);
  ns.push_back(mk_node("Reshape", {"ot", "sh_flat"}, {"out"}));
  g.nodes = std::move(ns);
}

int count_op(const Predictor& p, const char* op) {
  int c = 0;
  for (const auto& n : p.g.nodes)
    if (n.op == op) ++c;
  return c;
}

void run_with_qkv(Predictor* p, const std::vector<float>& q,
                  const std::vector<float>& k,
                  const std::vector<float>& v,
                  const std::vector<int64_t>& dims) {
  Tensor tq = mk_f32(dims, q), tk = mk_f32(dims, k), tv = mk_f32(dims, v);
  p->env["q"] = tq;
  p->env["k"] = tk;
  p->env["v"] = tv;
  p->build_stats_index();
  p->run();
}

void test_attention_fusion_parity() {
  // odd seq, masked and unmasked, plus the near-miss axis control
  for (int masked = 0; masked < 2; ++masked) {
    const int64_t b = 2, s = 5, h = 2, d = 3;
    std::mt19937 rng(7 + masked);
    std::uniform_real_distribution<float> dist(-1.f, 1.f);
    std::vector<float> q(size_t(b * s * h * d)), k(q.size()), v(q.size());
    for (auto& x : q) x = dist(rng);
    for (auto& x : k) x = dist(rng);
    for (auto& x : v) x = dist(rng);

    Predictor ref;
    build_attention_graph(&ref, b, s, h, d, masked != 0, 3);
    run_with_qkv(&ref, q, k, v, {b, s, h, d});

    Predictor fp;
    build_attention_graph(&fp, b, s, h, d, masked != 0, 3);
    std::map<std::string, std::vector<int64_t>> shp;
    std::map<std::string, int> dty;
    assert(fp.dry_run_shapes(&shp, &dty));
    fp.fuse_attention(shp);
    assert(count_op(fp, "PtpuAttention") == 1);
    assert(count_op(fp, "MatMul") == 0 && count_op(fp, "Exp") == 0);
    run_with_qkv(&fp, q, k, v, {b, s, h, d});

    assert(ref.outputs.size() == 1 && fp.outputs.size() == 1);
    assert(ref.outputs[0].dims == fp.outputs[0].dims);
    for (int64_t i = 0; i < ref.outputs[0].numel(); ++i) {
      const float a = ref.outputs[0].f[size_t(i)];
      const float bv = fp.outputs[0].f[size_t(i)];
      assert(std::fabs(a - bv) <= 1e-5f * (1.f + std::fabs(a)));
    }
  }
  // NEAR-MISS control: softmax over axis 2 (not last) must NOT fuse
  {
    Predictor nf;
    build_attention_graph(&nf, 2, 4, 2, 3, false, 2);
    std::map<std::string, std::vector<int64_t>> shp;
    std::map<std::string, int> dty;
    // the axis-2 ReduceMax makes Sub/Div shapes inconsistent with the
    // keepdim reshape targets, so the dry run itself may throw OR the
    // matcher must refuse — either way: no PtpuAttention node
    if (nf.dry_run_shapes(&shp, &dty)) nf.fuse_attention(shp);
    assert(count_op(nf, "PtpuAttention") == 0);
  }
}

void test_layernorm_fusion_parity() {
  const int64_t b = 2, s = 3, D = 4;
  Predictor ref, fp;
  for (Predictor* p : {&ref, &fp}) {
    Graph& g = p->g;
    g.input_names = {"x"};
    g.input_dims["x"] = {b, s, D};
    g.input_dtypes["x"] = DT_F32;
    g.output_names = {"out"};
    add_init(p, "axes", mk_i64({1}, {2}));
    add_init(p, "sh_keep", mk_i64({3}, {b, s, 1}));
    add_init(p, "Dc", mk_f32({}, {float(D)}));
    add_init(p, "eps", mk_f32({}, {1e-5f}));
    add_init(p, "negone", mk_f32({}, {-1.f}));
    add_init(p, "gamma", mk_f32({1, 1, D}, {1.5f, 0.5f, -2.f, 1.f}));
    add_init(p, "beta", mk_f32({1, 1, D}, {0.1f, -0.2f, 0.3f, 0.f}));
    add_init(p, "condc", mk_bool({b, s, 1}, std::vector<int64_t>(
                                                size_t(b * s), 1)));
    add_init(p, "altc",
             mk_f32({b, s, 1}, std::vector<float>(size_t(b * s),
                                                  std::nanf(""))));
    std::vector<Node> ns;
    Node r1 = mk_node("ReduceSum", {"x", "axes"}, {"s1"});
    set_ival(&r1, "keepdims", 0);
    ns.push_back(r1);
    ns.push_back(mk_node("Reshape", {"s1", "sh_keep"}, {"r1"}));
    ns.push_back(mk_node("Div", {"r1", "Dc"}, {"meanA"}));
    Node r2 = mk_node("ReduceSum", {"x", "axes"}, {"s2"});
    set_ival(&r2, "keepdims", 0);
    ns.push_back(r2);
    ns.push_back(mk_node("Reshape", {"s2", "sh_keep"}, {"r2"}));
    ns.push_back(mk_node("Div", {"r2", "Dc"}, {"meanB"}));
    ns.push_back(mk_node("Sub", {"x", "meanB"}, {"c2"}));
    ns.push_back(mk_node("Mul", {"c2", "c2"}, {"sq"}));
    Node r3 = mk_node("ReduceSum", {"sq", "axes"}, {"s3"});
    set_ival(&r3, "keepdims", 0);
    ns.push_back(r3);
    ns.push_back(mk_node("Reshape", {"s3", "sh_keep"}, {"r3"}));
    ns.push_back(mk_node("Div", {"r3", "Dc"}, {"var"}));
    ns.push_back(mk_node("Where", {"condc", "var", "altc"}, {"varg"}));
    ns.push_back(mk_node("Add", {"varg", "eps"}, {"ve"}));
    ns.push_back(mk_node("Sqrt", {"ve"}, {"sqv"}));
    ns.push_back(mk_node("Pow", {"sqv", "negone"}, {"rstd"}));
    ns.push_back(mk_node("Sub", {"x", "meanA"}, {"c1"}));
    ns.push_back(mk_node("Mul", {"c1", "rstd"}, {"m1"}));
    ns.push_back(mk_node("Mul", {"m1", "gamma"}, {"m2"}));
    ns.push_back(mk_node("Add", {"m2", "beta"}, {"out"}));
    g.nodes = std::move(ns);
  }
  std::mt19937 rng(11);
  std::uniform_real_distribution<float> dist(-2.f, 2.f);
  std::vector<float> x(size_t(b * s * D), 0.f);
  for (auto& v2 : x) v2 = dist(rng);
  const auto run_x = [&](Predictor* p) {
    p->env["x"] = mk_f32({b, s, D}, x);
    p->build_stats_index();
    p->run();
  };
  run_x(&ref);
  std::map<std::string, std::vector<int64_t>> shp;
  std::map<std::string, int> dty;
  assert(fp.dry_run_shapes(&shp, &dty));
  fp.fuse_layernorm(shp);
  assert(count_op(fp, "PtpuLayerNorm") == 1);
  assert(count_op(fp, "Sqrt") == 0 && count_op(fp, "ReduceSum") == 0);
  run_x(&fp);
  for (int64_t i = 0; i < ref.outputs[0].numel(); ++i) {
    const float a = ref.outputs[0].f[size_t(i)];
    const float bv = fp.outputs[0].f[size_t(i)];
    assert(std::fabs(a - bv) <= 1e-5f * (1.f + std::fabs(a)));
  }
}

void test_gelu_fusion_bitwise() {
  const int64_t n = 2 * 7;
  Predictor ref, fp;
  for (Predictor* p : {&ref, &fp}) {
    Graph& g = p->g;
    g.input_names = {"x"};
    g.input_dims["x"] = {2, 7};
    g.input_dtypes["x"] = DT_F32;
    g.output_names = {"out"};
    add_init(p, "three", mk_f32({}, {3.f}));
    add_init(p, "c1", mk_f32({}, {0.044715f}));
    add_init(p, "c2", mk_f32({}, {0.7978846f}));
    add_init(p, "one", mk_f32({}, {1.f}));
    add_init(p, "half", mk_f32({}, {0.5f}));
    std::vector<Node> ns;
    ns.push_back(mk_node("Pow", {"x", "three"}, {"p3"}));
    ns.push_back(mk_node("Mul", {"c1", "p3"}, {"m1"}));
    ns.push_back(mk_node("Add", {"x", "m1"}, {"a1"}));
    ns.push_back(mk_node("Mul", {"c2", "a1"}, {"m2"}));
    ns.push_back(mk_node("Tanh", {"m2"}, {"t"}));
    ns.push_back(mk_node("Add", {"one", "t"}, {"a2"}));
    ns.push_back(mk_node("Mul", {"half", "a2"}, {"m3"}));
    ns.push_back(mk_node("Mul", {"x", "m3"}, {"out"}));
    g.nodes = std::move(ns);
  }
  std::mt19937 rng(13);
  std::uniform_real_distribution<float> dist(-3.f, 3.f);
  std::vector<float> x(size_t(n), 0.f);
  for (auto& v2 : x) v2 = dist(rng);
  const auto run_x = [&](Predictor* p) {
    p->env["x"] = mk_f32({2, 7}, x);
    p->build_stats_index();
    p->run();
  };
  run_x(&ref);
  fp.fuse_gelu();
  assert(count_op(fp, "PtpuGelu") == 1 && fp.g.nodes.size() == 1);
  run_x(&fp);
  for (int64_t i = 0; i < n; ++i)   // same float ops, same order
    assert(ref.outputs[0].f[size_t(i)] == fp.outputs[0].f[size_t(i)]);
}

void test_gemm_i16_pair_path_exact() {
  // the VNNI pair-packed path (vpdpwssd where cpuid allows, generic
  // pair kernel otherwise) must match the scalar reference EXACTLY —
  // integer adds are associative, so any reordering is still ==
  std::mt19937 rng(17);
  std::uniform_int_distribution<int> dist(-128, 127);
  for (const auto& mnk : {std::array<int64_t, 3>{9, 13, 21},
                          std::array<int64_t, 3>{16, 16, 32},
                          std::array<int64_t, 3>{7, 33, 5}}) {
    const int64_t M = mnk[0], N = mnk[1], K = mnk[2];
    std::vector<int64_t> A(size_t(M * K)), B(size_t(K * N));
    for (auto& v : A) v = dist(rng);
    for (auto& v : B) v = dist(rng);
    std::vector<int32_t> C(size_t(M * N), 0);
    gemm_i16(A.data(), B.data(), C.data(), M, N, K, nullptr);
    for (int64_t m = 0; m < M; ++m)
      for (int64_t j = 0; j < N; ++j) {
        int64_t acc = 0;
        for (int64_t k = 0; k < K; ++k)
          acc += A[size_t(m * K + k)] * B[size_t(k * N + j)];
        assert(C[size_t(m * N + j)] == acc);
      }
  }
  std::printf("  gemm_i16 exact (vnni=%d, isa=%d)\n", int(isa_vnni()),
              isa_level());
}

/* ------------------------------------------------------------------
 * int4 weight-only path + persisted autotune (ISSUE 16)
 * ------------------------------------------------------------------ */

/* Decode the nibble panels back into a row-major [K,N] matrix — the
 * exact values the q4 kernels are contracted to multiply by. Padding
 * lanes (columns >= N inside the last panel) must reconstruct to
 * exactly 0.0f so fringe columns never leak into real outputs. */
std::vector<float> q4_unpack_ref(const std::vector<uint8_t>& q4,
                                 const std::vector<float>& qs,
                                 const std::vector<float>& qz, int64_t K,
                                 int64_t N, int64_t G) {
  const int64_t panels = (N + NR - 1) / NR, ng = q4_groups(K, G);
  std::vector<float> W(size_t(K * N), 0.f);
  for (int64_t p = 0; p < panels; ++p) {
    const uint8_t* pan = q4.data() + p * K * (NR / 2);
    const float* s = qs.data() + p * ng * NR;
    const float* z = qz.data() + p * ng * NR;
    for (int64_t k = 0; k < K; ++k) {
      const int64_t g = k / G;
      for (int64_t j = 0; j < NR; ++j) {
        const uint8_t byte = pan[size_t(k * (NR / 2) + (j & 7))];
        const int q = (j < 8) ? (byte & 0xF) : (byte >> 4);
        const float v = s[g * NR + j] * float(q) + z[g * NR + j];
        const int64_t col = p * NR + j;
        if (col < N)
          W[size_t(k * N + col)] = v;
        else
          assert(v == 0.f);
      }
    }
  }
  return W;
}

/* gemv_q4 / gemm_q4 against a double-precision reference over the
 * DEQUANTIZED weights: the factored epilogue (s*sum(a*q) + z*sum(a))
 * is algebraically identical, so only fp reassociation separates the
 * two. Shapes cover K not a multiple of the group size, a fringe
 * column panel, and K < G (single short group). */
void test_q4_kernels_match_dequant_reference() {
  std::mt19937 rng(29);
  std::uniform_real_distribution<float> d(-1.f, 1.f);
  const int64_t shapes[][3] = {  // {K, N, G}
      {70, 16, 32}, {64, 21, 64}, {130, 33, 64}, {24, 16, 64}};
  for (const auto& sh : shapes) {
    const int64_t K = sh[0], N = sh[1], G = sh[2], M = 4;
    std::vector<float> B(size_t(K * N)), A(size_t(M * K));
    for (auto& v : B) v = d(rng);
    for (auto& v : A) v = d(rng);
    std::vector<uint8_t> q4(size_t(q4_data_size(K, N)));
    std::vector<float> qs(size_t(q4_scale_size(K, N, G)), 0.f);
    std::vector<float> qz(size_t(q4_scale_size(K, N, G)), 0.f);
    assert(pack_b_q4(B.data(), K, N, G, q4.data(), qs.data(), qz.data()));
    const std::vector<float> W = q4_unpack_ref(q4, qs, qz, K, N, G);
    // quantization error bound: |W - B| <= scale/2 per element
    for (int64_t k = 0; k < K; ++k)
      for (int64_t j = 0; j < N; ++j) {
        const int64_t p = j / NR, g = k / G, ng = q4_groups(K, G);
        const float s = qs[size_t((p * ng + g) * NR + (j % NR))];
        assert(std::fabs(W[size_t(k * N + j)] - B[size_t(k * N + j)]) <=
               0.5f * s + 1e-6f);
      }
    std::vector<float> bias(size_t(N), 0.f);
    for (auto& v : bias) v = d(rng);
    std::vector<float> C(size_t(M * N), -99.f);
    gemm_q4(A.data(), q4.data(), qs.data(), qz.data(), C.data(), M, N, K,
            G, bias.data(), ACT_RELU, nullptr);
    std::vector<float> C1(size_t(N), -99.f);
    gemv_q4(A.data(), q4.data(), qs.data(), qz.data(), C1.data(), N, K, G,
            bias.data(), 0.f, ACT_RELU);
    for (int64_t m = 0; m < M; ++m)
      for (int64_t j = 0; j < N; ++j) {
        double acc = bias[size_t(j)];
        for (int64_t k = 0; k < K; ++k)
          acc += double(A[size_t(m * K + k)]) * double(W[size_t(k * N + j)]);
        const float want = float(acc > 0 ? acc : 0);
        assert(std::fabs(C[size_t(m * N + j)] - want) <= 1e-3f);
        if (m == 0) assert(std::fabs(C1[size_t(j)] - want) <= 1e-3f);
      }
  }
  std::printf("  q4 kernels vs dequant reference (isa=%d)\n", isa_level());
}

/* All-equal weight group: max == min gives scale 0 and the guard must
 * reconstruct the constant exactly (q=0, zp carries the value). */
void test_q4_all_equal_group_exact() {
  const int64_t K = 96, N = 20, G = 32;
  std::vector<float> B(size_t(K * N), 0.37f);
  std::vector<uint8_t> q4(size_t(q4_data_size(K, N)));
  std::vector<float> qs(size_t(q4_scale_size(K, N, G)), -1.f);
  std::vector<float> qz(size_t(q4_scale_size(K, N, G)), -1.f);
  assert(pack_b_q4(B.data(), K, N, G, q4.data(), qs.data(), qz.data()));
  const std::vector<float> W = q4_unpack_ref(q4, qs, qz, K, N, G);
  for (int64_t k = 0; k < K; ++k)
    for (int64_t j = 0; j < N; ++j)
      assert(W[size_t(k * N + j)] == 0.37f);  // EXACT, not approximate
}

/* Zero-extent q4 GEMM keeps the r11 empty-sum contract: K == 0 still
 * writes bias+act over the whole output (the arena planner skips
 * zero-fill on that promise); M == 0 / N == 0 are no-ops. Non-finite
 * weights must refuse to quantize (fp32 fallback at the call site). */
void test_q4_zero_extent_and_nonfinite() {
  const int64_t M = 5, N = 18, G = 64;
  std::vector<float> bias(size_t(N), 0.f);
  for (int64_t j = 0; j < N; ++j) bias[size_t(j)] = float(j) - 7.f;
  std::vector<float> C(size_t(M * N), -123.f);
  gemm_q4(nullptr, nullptr, nullptr, nullptr, C.data(), M, N, 0, G,
          bias.data(), ACT_RELU, nullptr);
  for (int64_t m = 0; m < M; ++m)
    for (int64_t j = 0; j < N; ++j)
      assert(C[size_t(m * N + j)] == std::max(0.f, bias[size_t(j)]));
  std::fill(C.begin(), C.end(), -123.f);
  gemm_q4(nullptr, nullptr, nullptr, nullptr, C.data(), 0, N, 8, G,
          nullptr, ACT_NONE, nullptr);
  gemm_q4(nullptr, nullptr, nullptr, nullptr, C.data(), M, 0, 8, G,
          nullptr, ACT_NONE, nullptr);
  for (float v : C) assert(v == -123.f);  // zero-extent never writes
  std::vector<float> B(size_t(16 * 16), 1.f);
  B[37] = std::numeric_limits<float>::quiet_NaN();
  std::vector<uint8_t> q4(size_t(q4_data_size(16, 16)));
  std::vector<float> qs(size_t(q4_scale_size(16, 16, G)), 0.f);
  std::vector<float> qz(size_t(q4_scale_size(16, 16, G)), 0.f);
  assert(!pack_b_q4(B.data(), 16, 16, G, q4.data(), qs.data(), qz.data()));
  B[37] = std::numeric_limits<float>::infinity();
  assert(!pack_b_q4(B.data(), 16, 16, G, q4.data(), qs.data(), qz.data()));
}

/* Quantization is a pure function of (B, K, N, G): two packs of the
 * same weights must be byte-identical — the artifact→load round trip
 * may not drift between processes or runs. */
void test_q4_pack_deterministic() {
  std::mt19937 rng(31);
  std::uniform_real_distribution<float> d(-2.f, 2.f);
  const int64_t K = 100, N = 40, G = 32;
  std::vector<float> B(size_t(K * N));
  for (auto& v : B) v = d(rng);
  std::vector<uint8_t> qa(size_t(q4_data_size(K, N)));
  std::vector<uint8_t> qb(size_t(q4_data_size(K, N)), 0xEE);
  std::vector<float> sa(size_t(q4_scale_size(K, N, G)), 0.f), sb = sa;
  std::vector<float> za = sa, zb = sa;
  assert(pack_b_q4(B.data(), K, N, G, qa.data(), sa.data(), za.data()));
  assert(pack_b_q4(B.data(), K, N, G, qb.data(), sb.data(), zb.data()));
  assert(qa == qb && sa == sb && za == zb);
}

/* Tune cache wire format: round trip, then every corruption class the
 * fuzz target covers must come back kMalformed (whole-file distrust —
 * a bad record rejects everything) and wrong machine kWrongCpu. */
void test_tune_cache_parse() {
  namespace tn = ptpu::tune;
  std::vector<std::pair<tn::TuneKey, tn::TuneConfig>> in, out;
  tn::TuneKey k1;
  k1.m = 4;
  k1.n = 512;
  k1.k = 128;
  k1.dtype = tn::kDtF32;
  tn::TuneConfig c1;
  c1.path = tn::kPathAlt;
  c1.kc = 160;
  c1.mult = 2;
  tn::TuneKey k2;
  k2.m = 0;
  k2.n = 64;
  k2.k = 96;
  k2.dtype = tn::kDtQ4Pack;
  tn::TuneConfig c2;
  c2.group = 32;
  in.push_back({k1, c1});
  in.push_back({k2, c2});
  const uint64_t sig = tn::CpuSig();
  std::vector<uint8_t> bytes;
  tn::SerializeCache(in, sig, &bytes);
  assert(bytes.size() ==
         tn::kTuneHeaderBytes + in.size() * tn::kTuneRecordBytes);
  assert(tn::ParseCacheBytes(bytes.data(), bytes.size(), sig, &out) ==
         tn::ParseResult::kOk);
  assert(out.size() == 2 && out[0].first.n == 512 &&
         out[0].second.path == tn::kPathAlt && out[1].second.group == 32);
  // wrong machine: recognizable file, different cpu signature
  assert(tn::ParseCacheBytes(bytes.data(), bytes.size(), sig ^ 0x5a5a,
                             &out) == tn::ParseResult::kWrongCpu);
  // truncated / padded: the size must match the header's count exactly
  assert(tn::ParseCacheBytes(bytes.data(), bytes.size() - 1, sig, &out) ==
         tn::ParseResult::kMalformed);
  std::vector<uint8_t> padded = bytes;
  padded.push_back(0);
  assert(tn::ParseCacheBytes(padded.data(), padded.size(), sig, &out) ==
         tn::ParseResult::kMalformed);
  assert(tn::ParseCacheBytes(bytes.data(), 3, sig, &out) == tn::ParseResult::kMalformed);
  assert(tn::ParseCacheBytes(bytes.data(), 0, sig, &out) == tn::ParseResult::kMalformed);
  // bad magic / bad version
  std::vector<uint8_t> bad = bytes;
  bad[0] ^= 0xFF;
  assert(tn::ParseCacheBytes(bad.data(), bad.size(), sig, &out) ==
         tn::ParseResult::kMalformed);
  bad = bytes;
  bad[4] = 99;
  assert(tn::ParseCacheBytes(bad.data(), bad.size(), sig, &out) ==
         tn::ParseResult::kMalformed);
  // huge count with a body that can't hold it
  bad = bytes;
  bad[16] = 0xFF;
  bad[17] = 0xFF;
  bad[18] = 0xFF;
  bad[19] = 0x7F;
  assert(tn::ParseCacheBytes(bad.data(), bad.size(), sig, &out) ==
         tn::ParseResult::kMalformed);
  // one out-of-range record poisons the whole file (group > 4096 at
  // record 1: offset header + record + {24 dims, 4 dtype, 12 cfg})
  bad = bytes;
  bad[tn::kTuneHeaderBytes + tn::kTuneRecordBytes + 41] = 0xFF;
  assert(tn::ParseCacheBytes(bad.data(), bad.size(), sig, &out) ==
         tn::ParseResult::kMalformed);
  // empty cache is valid
  tn::SerializeCache({}, sig, &bytes);
  assert(tn::ParseCacheBytes(bytes.data(), bytes.size(), sig, &out) ==
             tn::ParseResult::kOk &&
         out.empty());
}

/* Registry semantics: first-insert-wins, invalid configs rejected,
 * save→clear→load round trip through a real file, corrupt file and
 * missing file adopt nothing (silent re-probe contract). */
void test_tune_registry_persist() {
  namespace tn = ptpu::tune;
  auto& R = tn::Registry::Inst();
  R.Clear();
  tn::TuneKey key;
  key.m = 6;
  key.n = 256;
  key.k = 64;
  key.dtype = tn::kDtF32;
  tn::TuneConfig cfg;
  cfg.kc = 640;
  cfg.mult = 4;
  R.Insert(key, cfg);
  tn::TuneConfig later;
  later.kc = 160;
  R.Insert(key, later);  // loser: first probe result stays
  tn::TuneConfig got;
  assert(R.Lookup(key, &got) && got.kc == 640 && got.mult == 4);
  tn::TuneKey bad_key = key;
  bad_key.n = 999;
  tn::TuneConfig bad_cfg;
  bad_cfg.group = 99999;  // out of range: must be dropped
  R.Insert(bad_key, bad_cfg);
  assert(!R.Lookup(bad_key, &got));
  const std::string path = "/tmp/ptpu_selftest_tune.cache";
  assert(R.SaveIfDirty(path) == 1);
  R.Clear();
  assert(!R.Lookup(key, &got));
  assert(R.LoadFile(path) == 1);
  assert(R.Lookup(key, &got) && got.kc == 640);
  // corrupt the file on disk: load adopts nothing, never crashes
  {
    std::ifstream f(path, std::ios::binary);
    std::vector<char> buf((std::istreambuf_iterator<char>(f)),
                          std::istreambuf_iterator<char>());
    buf[8] ^= 0x1;  // cpu signature byte
    std::ofstream o(path, std::ios::binary | std::ios::trunc);
    o.write(buf.data(), std::streamsize(buf.size()));
  }
  R.Clear();
  assert(R.LoadFile(path) == 0 && R.Entries() == 0);
  ::unlink(path.c_str());
  R.Clear();
  assert(R.LoadFile(path) == 0);  // missing file: clean start
  assert(!R.StatsJson().empty() && R.StatsJson()[0] == '{');
  R.Clear();
}

/* Tune configs on the fp32 macro kernel: kc/mult re-block the same
 * k-ascending accumulation, so outputs are bitwise-equal to the
 * default; the kPathAlt row-GEMV keeps the order but may contract
 * differently, so it gets a tolerance. probe_gemm_cfg must try every
 * candidate and return a valid config. */
void test_tune_cfg_paths_consistent() {
  namespace tn = ptpu::tune;
  std::mt19937 rng(37);
  std::uniform_real_distribution<float> d(-1.f, 1.f);
  const int64_t M = 4, N = 48, K = 700;  // K spans multiple kc blocks
  std::vector<float> A(size_t(M * K)), B(size_t(K * N));
  for (auto& v : A) v = d(rng);
  for (auto& v : B) v = d(rng);
  std::vector<float> Bp(size_t((N + NR - 1) / NR * K * NR));
  pack_b<float>(B.data(), K, N, Bp.data());
  std::vector<float> ref(size_t(M * N)), C(size_t(M * N));
  gemm_bias_act<float>(A.data(), B.data(), ref.data(), M, N, K, nullptr,
                       Bp.data(), nullptr, nullptr, ACT_NONE);
  tn::TuneConfig kc_cfg;
  kc_cfg.kc = 160;
  kc_cfg.mult = 2;
  gemm_bias_act<float>(A.data(), B.data(), C.data(), M, N, K, nullptr,
                       Bp.data(), nullptr, nullptr, ACT_NONE, &kc_cfg);
  for (size_t i = 0; i < C.size(); ++i) assert(C[i] == ref[i]);  // bitwise
  tn::TuneConfig alt;
  alt.path = tn::kPathAlt;
  gemm_bias_act<float>(A.data(), B.data(), C.data(), M, N, K, nullptr,
                       Bp.data(), nullptr, nullptr, ACT_NONE, &alt);
  for (size_t i = 0; i < C.size(); ++i)
    assert(std::fabs(C[i] - ref[i]) <= 1e-4f * float(K));
  int runs = 0;
  const auto cfg = probe_gemm_cfg(M, [&](const tn::TuneConfig* c) {
    ++runs;
    gemm_bias_act<float>(A.data(), B.data(), C.data(), M, N, K, nullptr,
                         Bp.data(), nullptr, nullptr, ACT_NONE, c);
  });
  assert(tn::config_valid(tn::kDtF32, cfg));
  assert(runs >= 2 * 2);  // >= (default + alt) x 2 reps even on 1 core
  for (size_t i = 0; i < C.size(); ++i)
    assert(std::fabs(C[i] - ref[i]) <= 1e-4f * float(K));
}

}  // namespace

int main() {
  test_sgemm_matches_naive();
  test_sgemm_propagates_nan_through_zero();
  test_igemm_exact();
  test_int8_exact_bounds();
  test_bcast_walk_matches_divmod();
  test_check_dims_rejects();
  test_parallel_for_covers_range();
  test_packed_gemm_fringe_sweep();
  test_gemm_bias_act_epilogue();
  test_workpool_two_thread_stress();
  test_plan_arena_reuses_offsets();
  test_pack_b_im2col_matches_reference();
  test_predictor_run_stats_accumulate();
  test_attention_fusion_parity();
  test_layernorm_fusion_parity();
  test_gelu_fusion_bitwise();
  test_gemm_i16_pair_path_exact();
  test_q4_kernels_match_dequant_reference();
  test_q4_all_equal_group_exact();
  test_q4_zero_extent_and_nonfinite();
  test_q4_pack_deterministic();
  test_tune_cache_parse();
  test_tune_registry_persist();
  test_tune_cfg_paths_consistent();
  std::printf("ptpu_selftest: all native unit tests passed\n");
  return 0;
}
