// Native unit tests for the predictor TU internals — the cc_test
// analogue (reference: gtest cc_test targets per CMakeLists, e.g.
// `paddle/fluid/framework/data_type_test.cc`). Plain asserts, no test
// framework dependency; exit 0 = pass. Includes the predictor TU
// directly so the anonymous-namespace kernels (sgemm/igemm/bcast_walk/
// int8_exact/check_dims) are testable without widening their linkage.
//
// Build + run: make selftest (csrc/Makefile); wrapped by
// tests/test_native_selftest.py.
#include "ptpu_predictor.cc"

// asserts ARE the test — never compile them out, even under a
// release-style CXXFLAGS override carrying -DNDEBUG
#undef NDEBUG
#include <cassert>
#include <cstdio>
#include <random>

namespace {

void test_sgemm_matches_naive() {
  std::mt19937 rng(0);
  std::uniform_real_distribution<float> d(-1.f, 1.f);
  const int64_t M = 17, N = 33, K = 29;
  std::vector<float> A(M * K), B(K * N), C(M * N), ref(M * N, 0.f);
  for (auto& v : A) v = d(rng);
  for (auto& v : B) v = d(rng);
  sgemm(A.data(), B.data(), C.data(), M, N, K);
  for (int64_t m = 0; m < M; ++m)
    for (int64_t j = 0; j < N; ++j) {
      float acc = 0.f;
      for (int64_t k = 0; k < K; ++k) acc += A[m * K + k] * B[k * N + j];
      ref[m * N + j] = acc;
    }
  for (int64_t i = 0; i < M * N; ++i)
    assert(std::fabs(C[i] - ref[i]) <= 1e-4f * (1.f + std::fabs(ref[i])));
}

void test_sgemm_propagates_nan_through_zero() {
  // IEEE: 0 * NaN must stay NaN (the zero-skip regression guard)
  const float nan = std::nanf("");
  std::vector<float> A{0.f, 1.f}, B{nan, 2.f}, C(1);
  sgemm(A.data(), B.data(), C.data(), 1, 1, 2);
  assert(std::isnan(C[0]));
}

void test_igemm_exact() {
  std::mt19937 rng(1);
  std::uniform_int_distribution<int> d(-128, 127);
  const int64_t M = 9, N = 13, K = 21;
  std::vector<int32_t> A(M * K), B(K * N), C(M * N);
  for (auto& v : A) v = d(rng);
  for (auto& v : B) v = d(rng);
  igemm(A.data(), B.data(), C.data(), M, N, K);
  for (int64_t m = 0; m < M; ++m)
    for (int64_t j = 0; j < N; ++j) {
      int64_t acc = 0;
      for (int64_t k = 0; k < K; ++k)
        acc += int64_t(A[m * K + k]) * B[k * N + j];
      assert(C[m * N + j] == acc);
    }
}

void test_int8_exact_bounds() {
  std::vector<int64_t> ok{-128, 127, 0}, bad{-129}, big{128};
  const int64_t kmax = (int64_t(1) << 31) / (128 * 128);
  assert(int8_exact(ok, ok, kmax - 1));
  assert(!int8_exact(ok, ok, kmax));      // strict: 2^31 would overflow
  assert(!int8_exact(bad, ok, 4));
  assert(!int8_exact(ok, big, 4));
}

void test_bcast_walk_matches_divmod() {
  // [2,3,4] (x) [3,1] -> [2,3,4]; compare odometer against bcast_index
  std::vector<int64_t> od{2, 3, 4}, ad{2, 3, 4}, bd{3, 1};
  bcast_walk(od, ad, bd, [&](int64_t k, int64_t ai, int64_t bi) {
    assert(ai == bcast_index(k, od, ad));
    assert(bi == bcast_index(k, od, bd));
  });
  // scalar operand
  std::vector<int64_t> sd{};
  bcast_walk(od, ad, sd, [&](int64_t, int64_t, int64_t bi) {
    assert(bi == 0);
  });
}

void test_check_dims_rejects() {
  int64_t neg[2] = {2, -1};
  bool threw = false;
  try {
    check_dims(neg, 2);
  } catch (const std::exception&) {
    threw = true;
  }
  assert(threw);
  int64_t huge[2] = {3037000500LL, 3037000500LL};
  threw = false;
  try {
    check_dims(huge, 2);
  } catch (const std::exception&) {
    threw = true;
  }
  assert(threw);
  check_dims(nullptr, 0);  // 0-d scalar is legal
}

void test_parallel_for_covers_range() {
  std::vector<int> hit(1000, 0);
  parallel_for(1000, 7, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) hit[size_t(i)]++;
  });
  for (int v : hit) assert(v == 1);
}

}  // namespace

int main() {
  test_sgemm_matches_naive();
  test_sgemm_propagates_nan_through_zero();
  test_igemm_exact();
  test_int8_exact_bounds();
  test_bcast_walk_matches_divmod();
  test_check_dims_rejects();
  test_parallel_for_covers_range();
  std::printf("ptpu_selftest: all native unit tests passed\n");
  return 0;
}
