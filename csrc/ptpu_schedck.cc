// The ptpu_schedck engine — see ptpu_schedck.h for the model. One
// global cooperative scheduler: engine state lives behind a RAW
// std::mutex / std::condition_variable pair (the engine is exempt
// from its own instrumentation, the same way lockdep's state().mu is
// exempt from rank checking). Exactly one managed thread owns the
// schedule at a time; every hook is
//     take engine lock -> mutate model state -> pick next thread ->
//     wait until elected -> release engine lock
// so successive decisions are totally ordered through the engine
// mutex and every explored interleaving is physically data-race free.
#ifndef PTPU_SCHEDCK
#error "ptpu_schedck.cc must be compiled with -DPTPU_SCHEDCK"
#endif

#include "ptpu_schedck.h"

#include <unistd.h>

#include <cinttypes>
#include <condition_variable>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace ptpu {
namespace schedck {
namespace {

// Hard per-schedule decision budget: exceeding it means a thread (or
// a set of threads) is spinning without the scenario converging — a
// modeled livelock, reported like a deadlock.
constexpr uint64_t kStepLimit = 1u << 20;

uint64_t Splitmix64(uint64_t& s) {
  uint64_t z = (s += 0x9e3779b97f4a7c15ull);
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ull;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebull;
  return z ^ (z >> 31);
}

int64_t EnvI64(const char* name, int64_t dflt) {
  const char* e = std::getenv(name);
  if (!e || !*e) return dflt;
  char* end = nullptr;
  const long long v = std::strtoll(e, &end, 10);
  return (end && *end == '\0') ? int64_t(v) : dflt;
}

struct Rec {
  enum class St {
    kRunnable,
    kBlockedMutex,    // obj = mutex address (exclusive wait)
    kBlockedShared,   // obj = shared-mutex address (reader wait)
    kBlockedCv,       // obj = condvar address, untimed
    kBlockedCvTimed,  // obj = condvar address, timed (stays enabled)
    kBlockedJoin,     // join_target = tid
    kBlockedPred,     // pred() re-evaluated at every decision
    kFinished,
  };
  int tid = 0;
  St st = St::kRunnable;
  const void* obj = nullptr;
  std::function<bool()> pred;
  bool timed_out = false;   // out-param of a timed cv wait
  int64_t prio = 0;         // pct only
  const char* where = "spawn";
  int join_target = -1;
  std::function<void()> fn;
  std::thread real;  // empty for thread 0 (the Explore caller)
};

struct MutexSt {
  int owner = -1;  // exclusive holder tid, -1 = free
  int shared = 0;  // reader count (SharedMutex only)
};

struct Engine {
  std::mutex mu;
  std::condition_variable cv;
  bool active = false;
  int running = -1;
  std::vector<std::unique_ptr<Rec>> threads;
  std::unordered_map<const void*, MutexSt> mutexes;

  // per-Explore
  const char* scenario = "";
  Options opt;
  uint64_t schedule_idx = 0;

  // per-schedule
  uint64_t step = 0;
  std::vector<int> trace;  // chosen tid per decision

  // dfs backtracking state: for every decision inside the branch
  // horizon, the enabled-set index chosen this schedule and how many
  // were enabled. `prefix` forces the replayed stem of the next
  // schedule.
  std::vector<int> dfs_prefix;
  std::vector<int> dfs_chosen;
  std::vector<int> dfs_width;

  // pct per-schedule state
  bool pct = false;
  uint64_t rng = 0;
  std::vector<uint64_t> change_steps;
  int64_t pct_floor = 0;     // descending priorities handed out at
                             // change points (always the new minimum)
  uint64_t est_len = 64;     // running estimate of schedule length

  // replay
  bool replaying = false;
  std::vector<int> replay_tids;
};

Engine& E() {
  static Engine* e = new Engine();
  return *e;
}

thread_local Rec* tl = nullptr;

bool ManagedActive() { return tl != nullptr && E().active; }

const char* StName(Rec::St s) {
  switch (s) {
    case Rec::St::kRunnable: return "runnable";
    case Rec::St::kBlockedMutex: return "blocked-mutex";
    case Rec::St::kBlockedShared: return "blocked-shared";
    case Rec::St::kBlockedCv: return "blocked-cv";
    case Rec::St::kBlockedCvTimed: return "blocked-cv-timed";
    case Rec::St::kBlockedJoin: return "blocked-join";
    case Rec::St::kBlockedPred: return "blocked-pred";
    case Rec::St::kFinished: return "finished";
  }
  return "?";
}

std::string TracePath() {
  Engine& e = E();
  if (e.opt.trace_out && *e.opt.trace_out) return e.opt.trace_out;
  const char* env = std::getenv("PTPU_SCHEDCK_TRACE_OUT");
  if (env && *env) return env;
  return std::string(e.scenario) + ".schedck-trace";
}

// Failure path: report + trace file + abort. Engine lock held by the
// caller; never returns.
[[noreturn]] void FailLocked(const char* what, const char* detail) {
  Engine& e = E();
  std::fprintf(stderr,
               "\n== ptpu_schedck: %s ==\n"
               "scenario %s  strategy %s  schedule %" PRIu64
               "  step %" PRIu64 "\n",
               what, e.scenario,
               e.replaying ? "replay" : (e.pct ? "pct" : "dfs"),
               e.schedule_idx, e.step);
  if (detail && *detail) std::fprintf(stderr, "  %s\n", detail);
  for (const auto& t : e.threads) {
    std::fprintf(stderr, "  thread %d: %s%s at %s\n", t->tid,
                 StName(t->st),
                 t->tid == e.running ? " (running)" : "", t->where);
  }
  const std::string path = TracePath();
  if (std::FILE* f = std::fopen(path.c_str(), "w")) {
    std::fprintf(f, "ptpu_schedck-trace v1\n");
    std::fprintf(f, "scenario %s\n", e.scenario);
    std::fprintf(f, "strategy %s\n",
                 e.replaying ? "replay" : (e.pct ? "pct" : "dfs"));
    std::fprintf(f, "schedule %" PRIu64 "\n", e.schedule_idx);
    std::fprintf(f, "decisions %zu\n", e.trace.size());
    for (size_t i = 0; i < e.trace.size(); ++i)
      std::fprintf(f, "%d%c", e.trace[i],
                   (i + 1 == e.trace.size()) ? '\n' : ' ');
    std::fflush(f);
    std::fclose(f);
    std::fprintf(stderr,
                 "decision trace written to %s — replay with "
                 "schedck::Replay(name, body, \"%s\")\n",
                 path.c_str(), path.c_str());
  } else {
    std::fprintf(stderr, "(could not write trace to %s)\n",
                 path.c_str());
  }
  std::fflush(stderr);
  std::abort();
}

void WakeMutexWaiters(const void* m) {
  for (auto& t : E().threads) {
    if ((t->st == Rec::St::kBlockedMutex ||
         t->st == Rec::St::kBlockedShared) &&
        t->obj == m) {
      t->st = Rec::St::kRunnable;  // re-checks availability on wake
      t->obj = nullptr;
    }
  }
}

// The single scheduling decision. Engine lock held. The caller has
// already set its own state (kRunnable for a pure yield, a blocked
// state otherwise); afterwards `running` names the elected thread.
void PickNextLocked() {
  Engine& e = E();
  // 1. re-evaluate modeled syscall predicates
  for (auto& t : e.threads) {
    if (t->st == Rec::St::kBlockedPred && t->pred && t->pred()) {
      t->st = Rec::St::kRunnable;
      t->pred = nullptr;
    }
  }
  // 2. the enabled set, in tid order (determinism)
  std::vector<Rec*> enabled;
  bool unfinished = false;
  for (auto& t : e.threads) {
    if (t->st != Rec::St::kFinished) unfinished = true;
    if (t->st == Rec::St::kRunnable ||
        t->st == Rec::St::kBlockedCvTimed)
      enabled.push_back(t.get());
  }
  if (enabled.empty()) {
    if (!unfinished) {  // scenario fully drained (last thread exiting)
      e.running = -1;
      e.cv.notify_all();
      return;
    }
    FailLocked("DEADLOCK (all threads blocked)", nullptr);
  }
  if (e.step >= kStepLimit)
    FailLocked("LIVELOCK (per-schedule step budget exhausted)",
               nullptr);
  // 3. choose
  size_t idx = 0;
  const int n = int(enabled.size());
  if (e.replaying) {
    if (e.step < e.replay_tids.size()) {
      const int want = e.replay_tids[e.step];
      bool found = false;
      for (size_t i = 0; i < enabled.size(); ++i) {
        if (enabled[i]->tid == want) { idx = i; found = true; break; }
      }
      if (!found)
        FailLocked("REPLAY DIVERGENCE",
                   "recorded thread not enabled at this step — the "
                   "scenario is nondeterministic or the trace is "
                   "stale");
    } else {
      idx = size_t(e.step) % size_t(n);  // past the recorded failure
    }
  } else if (e.pct) {
    // priority change point: demote the current top before electing
    for (uint64_t cs : e.change_steps) {
      if (cs == e.step) {
        Rec* top = enabled[0];
        for (Rec* r : enabled)
          if (r->prio > top->prio) top = r;
        top->prio = --e.pct_floor;
        break;
      }
    }
    for (size_t i = 1; i < enabled.size(); ++i)
      if (enabled[i]->prio > enabled[idx]->prio) idx = i;
  } else {  // dfs
    const int horizon = e.opt.depth;
    if (e.step < uint64_t(horizon)) {
      if (e.step < e.dfs_prefix.size()) {
        idx = size_t(e.dfs_prefix[e.step]);
        if (idx >= size_t(n))
          FailLocked("DFS DIVERGENCE",
                     "prefix index exceeds the enabled set — the "
                     "scenario is nondeterministic");
      } else {
        idx = 0;
      }
      e.dfs_chosen.push_back(int(idx));
      e.dfs_width.push_back(n);
    } else {
      idx = size_t(e.step) % size_t(n);  // round-robin for progress
    }
  }
  Rec* chosen = enabled[idx];
  e.trace.push_back(chosen->tid);
  ++e.step;
  if (chosen->st == Rec::St::kBlockedCvTimed) {
    // electing a timed cv waiter = its timeout fired
    chosen->st = Rec::St::kRunnable;
    chosen->obj = nullptr;
    chosen->timed_out = true;
  }
  e.running = chosen->tid;
  e.cv.notify_all();
}

void WaitElectedLocked(std::unique_lock<std::mutex>& lk) {
  Engine& e = E();
  while (e.running != tl->tid) e.cv.wait(lk);
}

// Pure yield decision: self stays runnable.
void YieldLocked(std::unique_lock<std::mutex>& lk, const char* where) {
  tl->where = where;
  PickNextLocked();
  WaitElectedLocked(lk);
}

// Block self with `st`/`obj`, hand the schedule over, return once
// re-elected (state back to kRunnable by then).
void BlockSelfLocked(std::unique_lock<std::mutex>& lk, Rec::St st,
                     const void* obj, const char* where) {
  tl->st = st;
  tl->obj = obj;
  tl->where = where;
  PickNextLocked();
  WaitElectedLocked(lk);
}

// Exclusive-acquire with the pre-acquire decision point. Engine lock
// held around the whole thing.
void AcquireMutexLocked(std::unique_lock<std::mutex>& lk,
                        const void* m, const char* where) {
  Engine& e = E();
  YieldLocked(lk, where);
  // re-look-up around every block: other threads insert into the map
  // while we are parked, which may rehash and move the node
  for (;;) {
    MutexSt& s = e.mutexes[m];
    if (s.owner == -1 && s.shared == 0) {
      s.owner = tl->tid;
      return;
    }
    BlockSelfLocked(lk, Rec::St::kBlockedMutex, m, where);
  }
}

int64_t NewPctPrio() {
  Engine& e = E();
  // positive random priority, low byte = tid for total order
  return int64_t((Splitmix64(e.rng) >> 2) & ~uint64_t(0xff)) |
         int64_t(e.threads.size() & 0xff);
}

void BeginSchedule() {
  Engine& e = E();
  std::lock_guard<std::mutex> lk(e.mu);
  e.threads.clear();
  e.mutexes.clear();
  e.trace.clear();
  e.dfs_chosen.clear();
  e.dfs_width.clear();
  e.step = 0;
  auto main_rec = std::make_unique<Rec>();
  main_rec->tid = 0;
  main_rec->where = "scenario-body";
  tl = main_rec.get();
  e.threads.push_back(std::move(main_rec));
  if (e.pct) {
    e.rng = (e.opt.seed ^ 0x243f6a8885a308d3ull) +
            e.schedule_idx * 0x9e3779b97f4a7c15ull;
    (void)Splitmix64(e.rng);
    e.pct_floor = 0;
    e.change_steps.clear();
    for (int i = 0; i < e.opt.depth; ++i)
      e.change_steps.push_back(1 + Splitmix64(e.rng) % e.est_len);
    e.threads[0]->prio = NewPctPrio();
  }
  e.running = 0;
  e.active = true;
}

// Returns true when another schedule should run.
bool EndSchedule(Result* res) {
  Engine& e = E();
  std::unique_lock<std::mutex> lk(e.mu);
  for (auto& t : e.threads) {
    if (t->tid != 0 && t->st != Rec::St::kFinished)
      FailLocked("SCENARIO PROTOCOL",
                 "body returned while spawned threads are still "
                 "live — join every schedck::Thread");
  }
  if (e.step > res->max_steps) res->max_steps = e.step;
  if (e.step > e.est_len) e.est_len = e.step;
  e.active = false;
  tl = nullptr;
  e.threads.clear();
  e.mutexes.clear();
  if (e.replaying) return false;
  if (e.pct) return e.schedule_idx + 1 < e.opt.max_schedules;
  // dfs backtrack: bump the deepest in-horizon decision that still
  // has an unexplored sibling, truncate the prefix there.
  for (int s = int(e.dfs_chosen.size()) - 1; s >= 0; --s) {
    if (e.dfs_chosen[s] + 1 < e.dfs_width[s]) {
      e.dfs_prefix.assign(e.dfs_chosen.begin(),
                          e.dfs_chosen.begin() + s + 1);
      e.dfs_prefix[s] += 1;
      if (e.schedule_idx + 1 >= e.opt.max_schedules)
        return false;  // budget cap: bounded space NOT exhausted
      return true;
    }
  }
  res->exhausted = true;
  return false;
}

void ResolveOptions(Options* opt) {
  if (opt->max_schedules == 0) {
    opt->max_schedules =
        uint64_t(EnvI64("PTPU_SCHEDCK_SCHEDULES", 1000));
    if (opt->max_schedules == 0) opt->max_schedules = 1;
  }
  if (opt->depth == 0) {
    opt->depth = int(EnvI64(
        "PTPU_SCHEDCK_DEPTH",
        opt->strategy == Options::Strategy::kDfs ? 6 : 3));
  }
  if (opt->seed == 0)
    opt->seed = uint64_t(EnvI64("PTPU_SCHEDCK_SEED", 1));
  const char* st = std::getenv("PTPU_SCHEDCK_STRATEGY");
  if (st && *st) {
    if (std::strcmp(st, "dfs") == 0)
      opt->strategy = Options::Strategy::kDfs;
    else if (std::strcmp(st, "pct") == 0)
      opt->strategy = Options::Strategy::kPct;
  }
}

Result RunExploration(const char* name,
                      const std::function<void()>& body,
                      const Options& opt) {
  Engine& e = E();
  {
    std::lock_guard<std::mutex> lk(e.mu);
    if (e.active) {
      std::fprintf(stderr,
                   "ptpu_schedck: nested Explore/Replay (scenario %s "
                   "inside %s)\n", name, e.scenario);
      std::abort();
    }
    e.scenario = name;
    e.opt = opt;
    e.pct = opt.strategy == Options::Strategy::kPct && !e.replaying;
    e.schedule_idx = 0;
    e.dfs_prefix.clear();
    e.est_len = 64;
  }
  Result res;
  for (;;) {
    BeginSchedule();
    body();
    res.schedules = e.schedule_idx + 1;
    const bool more = EndSchedule(&res);
    if (!more) break;
    ++e.schedule_idx;
  }
  return res;
}

}  // namespace

Result Explore(const char* name, const std::function<void()>& body,
               Options opt) {
  ResolveOptions(&opt);
  E().replaying = false;
  E().replay_tids.clear();
  return RunExploration(name, body, opt);
}

Result Replay(const char* name, const std::function<void()>& body,
              const char* trace_file) {
  Engine& e = E();
  std::vector<int> tids;
  std::FILE* f = std::fopen(trace_file, "r");
  if (!f) {
    std::fprintf(stderr, "ptpu_schedck: cannot open trace %s\n",
                 trace_file);
    std::abort();
  }
  char line[256];
  bool header_ok = false;
  long decisions = -1;
  while (std::fgets(line, sizeof(line), f)) {
    if (std::strncmp(line, "ptpu_schedck-trace", 18) == 0) {
      header_ok = true;
    } else if (std::sscanf(line, "decisions %ld", &decisions) == 1) {
      int tid;
      while (std::fscanf(f, "%d", &tid) == 1) tids.push_back(tid);
      break;
    }
  }
  std::fclose(f);
  if (!header_ok || decisions < 0 ||
      size_t(decisions) != tids.size()) {
    std::fprintf(stderr,
                 "ptpu_schedck: malformed trace %s (decisions %ld, "
                 "parsed %zu)\n", trace_file, decisions, tids.size());
    std::abort();
  }
  Options opt;
  ResolveOptions(&opt);
  opt.max_schedules = 1;
  e.replaying = true;
  e.replay_tids = std::move(tids);
  Result res = RunExploration(name, body, opt);
  e.replaying = false;
  e.replay_tids.clear();
  return res;
}

Thread::Thread(std::function<void()> fn) {
  Engine& e = E();
  std::unique_lock<std::mutex> lk(e.mu);
  if (!ManagedActive()) {
    std::fprintf(stderr,
                 "ptpu_schedck: schedck::Thread spawned outside an "
                 "active exploration\n");
    std::abort();
  }
  auto rec = std::make_unique<Rec>();
  Rec* rp = rec.get();
  rp->tid = int(e.threads.size());
  rp->fn = std::move(fn);
  if (e.pct) rp->prio = NewPctPrio();
  e.threads.push_back(std::move(rec));
  impl_ = rp;
  rp->real = std::thread([rp] {
    Engine& eng = E();
    std::unique_lock<std::mutex> l(eng.mu);
    tl = rp;
    WaitElectedLocked(l);
    l.unlock();
    rp->fn();
    l.lock();
    rp->st = Rec::St::kFinished;
    rp->where = "exit";
    for (auto& t : eng.threads) {
      if (t->st == Rec::St::kBlockedJoin &&
          t->join_target == rp->tid) {
        t->st = Rec::St::kRunnable;
        t->join_target = -1;
      }
    }
    PickNextLocked();
    tl = nullptr;
  });
  // spawn decision: run the child now, or keep going?
  YieldLocked(lk, "spawn");
}

Thread& Thread::operator=(Thread&& o) noexcept {
  if (this != &o) {
    if (impl_) {
      std::fprintf(stderr,
                   "ptpu_schedck: assignment over a joinable "
                   "schedck::Thread\n");
      std::abort();
    }
    impl_ = o.impl_;
    o.impl_ = nullptr;
  }
  return *this;
}

Thread::~Thread() {
  if (impl_) {
    std::fprintf(stderr,
                 "ptpu_schedck: schedck::Thread destroyed without "
                 "join()\n");
    std::abort();
  }
}

void Thread::join() {
  Engine& e = E();
  Rec* rp = static_cast<Rec*>(impl_);
  if (!rp) return;
  {
    std::unique_lock<std::mutex> lk(e.mu);
    while (rp->st != Rec::St::kFinished) {
      tl->join_target = rp->tid;
      BlockSelfLocked(lk, Rec::St::kBlockedJoin, nullptr, "join");
    }
  }
  rp->real.join();  // model-finished => the OS thread is exiting
  impl_ = nullptr;
}

void SchedPoint(const char* where) {
  if (!ManagedActive()) return;
  Engine& e = E();
  std::unique_lock<std::mutex> lk(e.mu);
  YieldLocked(lk, where);
}

void BlockUntil(const std::function<bool()>& pred, const char* what) {
  if (!ManagedActive()) {
    // unmanaged fall-back: the predicate must already hold (no
    // scheduler exists to make progress for us)
    if (!pred()) {
      std::fprintf(stderr,
                   "ptpu_schedck: BlockUntil(%s) outside an "
                   "exploration with a false predicate\n", what);
      std::abort();
    }
    return;
  }
  Engine& e = E();
  std::unique_lock<std::mutex> lk(e.mu);
  if (pred()) {
    YieldLocked(lk, what);
    return;
  }
  tl->pred = pred;
  BlockSelfLocked(lk, Rec::St::kBlockedPred, nullptr, what);
  tl->pred = nullptr;
}

bool Managed() { return ManagedActive(); }

void FailAssert(const char* expr, const char* file, int line) {
  Engine& e = E();
  char buf[512];
  std::snprintf(buf, sizeof(buf), "%s at %s:%d", expr, file, line);
  if (ManagedActive()) {
    std::unique_lock<std::mutex> lk(e.mu);
    tl->where = "assert";
    FailLocked("ASSERTION FAILED", buf);
  }
  std::fprintf(stderr, "ptpu_schedck: assertion failed: %s\n", buf);
  std::fflush(stderr);
  std::abort();
}

// --- ptpu_sync.h hooks --------------------------------------------

bool OnMutexLock(void* m) {
  if (!ManagedActive()) return false;
  Engine& e = E();
  std::unique_lock<std::mutex> lk(e.mu);
  AcquireMutexLocked(lk, m, "mutex.lock");
  return true;
}

bool OnMutexTryLock(void* m, bool* acquired) {
  if (!ManagedActive()) return false;
  Engine& e = E();
  std::unique_lock<std::mutex> lk(e.mu);
  YieldLocked(lk, "mutex.try_lock");
  MutexSt& s = e.mutexes[m];
  if (s.owner == -1 && s.shared == 0) {
    s.owner = tl->tid;
    *acquired = true;
  } else {
    *acquired = false;
  }
  return true;
}

bool OnMutexUnlock(void* m) {
  if (!ManagedActive()) return false;
  Engine& e = E();
  std::unique_lock<std::mutex> lk(e.mu);
  MutexSt& s = e.mutexes[m];
  if (s.owner != tl->tid)
    FailLocked("MUTEX PROTOCOL", "unlock by a non-owner");
  s.owner = -1;
  WakeMutexWaiters(m);
  YieldLocked(lk, "mutex.unlock");  // post-release decision point
  return true;
}

bool OnSharedLock(void* m) { return OnMutexLock(m); }

bool OnSharedUnlock(void* m) { return OnMutexUnlock(m); }

bool OnSharedLockShared(void* m) {
  if (!ManagedActive()) return false;
  Engine& e = E();
  std::unique_lock<std::mutex> lk(e.mu);
  YieldLocked(lk, "shared.lock_shared");
  while (e.mutexes[m].owner != -1) {
    BlockSelfLocked(lk, Rec::St::kBlockedShared, m,
                    "shared.lock_shared");
  }
  e.mutexes[m].shared += 1;
  return true;
}

bool OnSharedUnlockShared(void* m) {
  if (!ManagedActive()) return false;
  Engine& e = E();
  std::unique_lock<std::mutex> lk(e.mu);
  MutexSt& s = e.mutexes[m];
  if (s.shared <= 0)
    FailLocked("MUTEX PROTOCOL",
               "unlock_shared without a shared hold");
  s.shared -= 1;
  WakeMutexWaiters(m);
  YieldLocked(lk, "shared.unlock_shared");
  return true;
}

bool OnCvWait(void* cvp, void* mp, int64_t usec) {
  if (!ManagedActive()) return false;
  Engine& e = E();
  std::unique_lock<std::mutex> lk(e.mu);
  MutexSt& s = e.mutexes[mp];
  if (s.owner != tl->tid)
    FailLocked("CV PROTOCOL", "wait without holding the mutex");
  s.owner = -1;
  WakeMutexWaiters(mp);
  tl->timed_out = false;
  BlockSelfLocked(lk,
                  usec < 0 ? Rec::St::kBlockedCv
                           : Rec::St::kBlockedCvTimed,
                  cvp, usec < 0 ? "cv.wait" : "cv.wait_timed");
  AcquireMutexLocked(lk, mp, "cv.reacquire");
  return true;
}

bool OnCvNotify(void* cvp) {
  if (!ManagedActive()) return false;
  Engine& e = E();
  std::unique_lock<std::mutex> lk(e.mu);
  // Wake EVERY waiter, for notify_one too: a sound over-approximation
  // (spurious wakeups are legal for std::condition_variable, and the
  // wrappers only expose predicate waits). Lost wakeups still show:
  // an untimed wait that nobody notifies never re-enters the enabled
  // set, so the schedule that strands it deadlocks.
  for (auto& t : e.threads) {
    if ((t->st == Rec::St::kBlockedCv ||
         t->st == Rec::St::kBlockedCvTimed) &&
        t->obj == cvp) {
      t->st = Rec::St::kRunnable;
      t->obj = nullptr;
    }
  }
  YieldLocked(lk, "cv.notify");
  return true;
}

}  // namespace schedck
}  // namespace ptpu
