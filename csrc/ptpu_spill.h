// KV-cache spill tier (ISSUE 19): the disk half of KV tiering +
// session hibernation. Three byte formats live here, all following
// the r11 untrusted-file posture established by ptpu_tune.h /
// ptpu_capture.h — versioned magic + fixed-size header + fixed-size
// records through the bounds-checked ptpu_wire.h codecs, an
// exact-size check BEFORE any record read, and whole-file reject on
// ANY malformed byte (csrc/fuzz/fuzz_spill.cc fuzzes every parser
// below):
//
//   1. The SPILL FILE header ("PSPL"): an mmap'd slot store of
//      fixed-size page-group slabs. KV page groups are contiguous
//      [layer][k|v][token][H][D] float slabs — natural disk records —
//      so a cold group spills as one slot write and restores as one
//      slot read. Slot CONTENT is per-process scratch (the
//      hibernation registry that gives slots meaning lives in KvPool
//      RAM), so Attach always resets the file; the header exists so a
//      foreign/corrupt file at the configured path is detected and
//      counted instead of silently scribbled over.
//
//   2. HIBERNATION RECORDS ("PHIB"): a serialized idle session —
//      length + per-group (kind, gid|slot, gen) rows. The bytes are a
//      HANDLE, not a capability: KvPool::restore() cross-validates
//      every field against its RAM-side registry entry and rejects on
//      any mismatch, so malformed or replayed bytes can error but
//      never corrupt the pool.
//
//   3. The PREFIX-PERSIST FILE ("PPFX"): the content-addressed adopt
//      index serialized across restarts, parent-before-child. Safety
//      matches the r12 in-RAM argument: the chain hash is recomputed
//      from the PERSISTED TOKEN IDS on load (never read from the
//      file), and adoption still exact-matches token ids + parent
//      (gid,gen) linkage — a warmed cache can only miss, never serve
//      wrong KV for a different token sequence. A corrupted payload
//      is caught by the per-record checksum; the whole file rejects.
//
// Concurrency: SpillFile has its own ranked mutex (kv.spill, rank 28)
// taken strictly UNDER kv.pool (25) — KvPool calls into the slot
// store while holding its pool lock — and above nothing: SpillFile
// never calls out. See the README lock-rank table.
#ifndef PTPU_SPILL_H_
#define PTPU_SPILL_H_

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

#include "ptpu_sync.h"
#include "ptpu_wire.h"

namespace ptpu {
namespace spill {

// ---------------------------------------------------------------- formats
// Spill-file header (one per file, in a 4096-byte reserved region so
// slot offsets stay page-aligned for mmap):
//   [u32 magic "PSPL"][u32 version][u32 page][u32 layers][u32 heads]
//   [u32 hdim][u64 slot_bytes]
constexpr uint32_t kSpillMagic = 0x4c505350u;  // "PSPL" little-endian
constexpr uint32_t kSpillVersion = 1;
constexpr size_t kSpillHeaderBytes = 32;  // 24 used + 8 spare (zero)
constexpr size_t kSpillHeaderReserve = 4096;
constexpr int64_t kSpillChunkSlots = 64;  // mmap growth granule

// Hibernation record:
//   [u32 magic "PHIB"][u32 version][u64 hib_id][u64 len]
//   [u32 ngroups][u32 reserved=0]
//   then ngroups x [u32 kind][u32 reserved=0][i64 a][u64 b]
// kind 0 = shared (a=gid, b=gen: the record HOLDS a pool ref);
// kind 1 = spilled (a=spill slot, b=0).
constexpr uint32_t kHibMagic = 0x42494850u;  // "PHIB" little-endian
constexpr uint32_t kHibVersion = 1;
constexpr size_t kHibHeaderBytes = 32;
constexpr size_t kHibRecordBytes = 24;
constexpr uint32_t kHibMaxGroups = 1u << 20;
constexpr uint64_t kHibMaxLen = 1ull << 40;
constexpr uint32_t kHibKindShared = 0;
constexpr uint32_t kHibKindSpilled = 1;

// Prefix-persist file:
//   [u32 magic "PPFX"][u32 version][u32 page][u32 layers][u32 heads]
//   [u32 hdim][u32 count][u32 reserved=0]
//   then count x [u32 parent_idx][u32 ntoks=page][page x i64 tokens]
//                [group_elems x f32 payload][u64 fnv1a checksum]
// parent_idx refers to an EARLIER record in the same file (or
// kPrefixRootParent) — parent-before-child order is part of the
// format, so a single forward pass rebuilds the chain.
constexpr uint32_t kPrefixMagic = 0x58465050u;  // "PPFX" little-endian
constexpr uint32_t kPrefixVersion = 1;
constexpr size_t kPrefixHeaderBytes = 32;
constexpr uint32_t kPrefixMaxRecords = 65536;
constexpr uint32_t kPrefixRootParent = 0xffffffffu;

// geometry caps: keep every derived size computable in uint64 with
// headroom (max slot_bytes under these caps is ~2^55)
constexpr uint32_t kMaxPage = 4096;
constexpr uint32_t kMaxLayers = 1024;
constexpr uint32_t kMaxHeads = 4096;
constexpr uint32_t kMaxHdim = 65536;

enum class ParseResult { kOk, kMalformed };

struct SpillGeom {
  uint32_t page = 0, layers = 0, heads = 0, hdim = 0;
  uint64_t slot_bytes = 0;  // == layers * 2 * page * heads * hdim * 4
};

inline bool GeomValid(const SpillGeom& g) {
  if (g.page < 1 || g.page > kMaxPage) return false;
  if (g.layers < 1 || g.layers > kMaxLayers) return false;
  if (g.heads < 1 || g.heads > kMaxHeads) return false;
  if (g.hdim < 1 || g.hdim > kMaxHdim) return false;
  const uint64_t want = uint64_t(g.layers) * 2 * g.page * g.heads *
                        g.hdim * sizeof(float);
  return g.slot_bytes == want;
}

inline uint64_t GeomElems(const SpillGeom& g) {
  return uint64_t(g.layers) * 2 * g.page * g.heads * g.hdim;
}

inline void SerializeSpillHeader(const SpillGeom& g,
                                 uint8_t out[kSpillHeaderBytes]) {
  std::memset(out, 0, kSpillHeaderBytes);
  PutU32(out + 0, kSpillMagic);
  PutU32(out + 4, kSpillVersion);
  PutU32(out + 8, g.page);
  PutU32(out + 12, g.layers);
  PutU32(out + 16, g.heads);
  PutU32(out + 20, g.hdim);
  PutU64(out + 24, g.slot_bytes);
}

inline ParseResult ParseSpillHeader(const uint8_t* data, size_t size,
                                    SpillGeom* out) {
  if (data == nullptr || size < kSpillHeaderBytes)
    return ParseResult::kMalformed;
  if (GetU32(data + 0) != kSpillMagic) return ParseResult::kMalformed;
  if (GetU32(data + 4) != kSpillVersion) return ParseResult::kMalformed;
  SpillGeom g;
  g.page = GetU32(data + 8);
  g.layers = GetU32(data + 12);
  g.heads = GetU32(data + 16);
  g.hdim = GetU32(data + 20);
  g.slot_bytes = GetU64(data + 24);
  if (!GeomValid(g)) return ParseResult::kMalformed;
  *out = g;
  return ParseResult::kOk;
}

// -------------------------------------------------------- hibernation
struct HibGroup {
  uint32_t kind = 0;
  int64_t a = 0;   // kind 0: gid | kind 1: spill slot
  uint64_t b = 0;  // kind 0: gen | kind 1: 0
};

struct HibRecord {
  uint64_t hib_id = 0;
  uint64_t len = 0;
  std::vector<HibGroup> groups;
};

inline void SerializeHib(const HibRecord& r, std::vector<uint8_t>* out) {
  out->assign(kHibHeaderBytes + r.groups.size() * kHibRecordBytes, 0);
  uint8_t* p = out->data();
  PutU32(p + 0, kHibMagic);
  PutU32(p + 4, kHibVersion);
  PutU64(p + 8, r.hib_id);
  PutU64(p + 16, r.len);
  PutU32(p + 24, uint32_t(r.groups.size()));
  for (size_t i = 0; i < r.groups.size(); ++i) {
    uint8_t* q = p + kHibHeaderBytes + i * kHibRecordBytes;
    PutU32(q + 0, r.groups[i].kind);
    PutI64(q + 8, r.groups[i].a);
    PutU64(q + 16, r.groups[i].b);
  }
}

inline ParseResult ParseHibBytes(const uint8_t* data, size_t size,
                                 HibRecord* out) {
  if (data == nullptr || size < kHibHeaderBytes)
    return ParseResult::kMalformed;
  if (GetU32(data + 0) != kHibMagic) return ParseResult::kMalformed;
  if (GetU32(data + 4) != kHibVersion) return ParseResult::kMalformed;
  const uint64_t len = GetU64(data + 16);
  const uint32_t n = GetU32(data + 24);
  if (len > kHibMaxLen || n > kHibMaxGroups)
    return ParseResult::kMalformed;
  if (GetU32(data + 28) != 0) return ParseResult::kMalformed;
  // exact size BEFORE any record read (the r11 rule)
  if (size != kHibHeaderBytes + size_t(n) * kHibRecordBytes)
    return ParseResult::kMalformed;
  HibRecord r;
  r.hib_id = GetU64(data + 8);
  r.len = len;
  r.groups.resize(n);
  for (uint32_t i = 0; i < n; ++i) {
    const uint8_t* q = data + kHibHeaderBytes + size_t(i) * kHibRecordBytes;
    HibGroup& g = r.groups[i];
    g.kind = GetU32(q + 0);
    if (GetU32(q + 4) != 0) return ParseResult::kMalformed;
    g.a = GetI64(q + 8);
    g.b = GetU64(q + 16);
    if (g.kind != kHibKindShared && g.kind != kHibKindSpilled)
      return ParseResult::kMalformed;
    if (g.a < 0) return ParseResult::kMalformed;
    if (g.kind == kHibKindSpilled && g.b != 0)
      return ParseResult::kMalformed;
  }
  out->groups.swap(r.groups);  // adopt only on full success
  out->hib_id = r.hib_id;
  out->len = r.len;
  return ParseResult::kOk;
}

// ------------------------------------------------------ prefix persist
struct PrefixRec {
  uint32_t parent = kPrefixRootParent;  // index of an EARLIER record
  std::vector<int64_t> toks;            // exactly `page` ids
  std::vector<float> vals;              // exactly group_elems floats
};

inline uint64_t Fnv1a(const uint8_t* p, size_t n) {
  uint64_t h = 0xcbf29ce484222325ull;
  for (size_t i = 0; i < n; ++i) {
    h ^= p[i];
    h *= 0x100000001b3ull;
  }
  return h;
}

inline uint64_t PrefixRecordBytes(const SpillGeom& g) {
  return 8 + uint64_t(g.page) * 8 + GeomElems(g) * 4 + 8;
}

inline void SerializePrefix(const std::vector<PrefixRec>& recs,
                            const SpillGeom& g,
                            std::vector<uint8_t>* out) {
  const uint64_t rec_bytes = PrefixRecordBytes(g);
  out->assign(kPrefixHeaderBytes + recs.size() * rec_bytes, 0);
  uint8_t* p = out->data();
  PutU32(p + 0, kPrefixMagic);
  PutU32(p + 4, kPrefixVersion);
  PutU32(p + 8, g.page);
  PutU32(p + 12, g.layers);
  PutU32(p + 16, g.heads);
  PutU32(p + 20, g.hdim);
  PutU32(p + 24, uint32_t(recs.size()));
  for (size_t i = 0; i < recs.size(); ++i) {
    uint8_t* q = p + kPrefixHeaderBytes + i * rec_bytes;
    PutU32(q + 0, recs[i].parent);
    PutU32(q + 4, g.page);
    for (uint32_t t = 0; t < g.page; ++t)
      PutI64(q + 8 + size_t(t) * 8, recs[i].toks[t]);
    uint8_t* v = q + 8 + size_t(g.page) * 8;
    for (uint64_t e = 0; e < GeomElems(g); ++e)
      PutF32(v + e * 4, recs[i].vals[size_t(e)]);
    PutU64(q + rec_bytes - 8, Fnv1a(q, size_t(rec_bytes) - 8));
  }
}

inline ParseResult ParsePrefixBytes(const uint8_t* data, size_t size,
                                    const SpillGeom& g,
                                    std::vector<PrefixRec>* out) {
  if (data == nullptr || size < kPrefixHeaderBytes || !GeomValid(g))
    return ParseResult::kMalformed;
  if (GetU32(data + 0) != kPrefixMagic) return ParseResult::kMalformed;
  if (GetU32(data + 4) != kPrefixVersion) return ParseResult::kMalformed;
  if (GetU32(data + 8) != g.page || GetU32(data + 12) != g.layers ||
      GetU32(data + 16) != g.heads || GetU32(data + 20) != g.hdim)
    return ParseResult::kMalformed;
  const uint32_t count = GetU32(data + 24);
  if (count > kPrefixMaxRecords) return ParseResult::kMalformed;
  if (GetU32(data + 28) != 0) return ParseResult::kMalformed;
  const uint64_t rec_bytes = PrefixRecordBytes(g);
  // exact size BEFORE any record read (the r11 rule)
  if (uint64_t(size) != kPrefixHeaderBytes + uint64_t(count) * rec_bytes)
    return ParseResult::kMalformed;
  std::vector<PrefixRec> recs(count);
  for (uint32_t i = 0; i < count; ++i) {
    const uint8_t* q = data + kPrefixHeaderBytes + size_t(i) * rec_bytes;
    PrefixRec& r = recs[i];
    r.parent = GetU32(q + 0);
    if (r.parent != kPrefixRootParent && r.parent >= i)
      return ParseResult::kMalformed;
    if (GetU32(q + 4) != g.page) return ParseResult::kMalformed;
    if (GetU64(q + rec_bytes - 8) != Fnv1a(q, size_t(rec_bytes) - 8))
      return ParseResult::kMalformed;
    r.toks.resize(g.page);
    for (uint32_t t = 0; t < g.page; ++t)
      r.toks[t] = GetI64(q + 8 + size_t(t) * 8);
    const uint8_t* v = q + 8 + size_t(g.page) * 8;
    r.vals.resize(size_t(GeomElems(g)));
    for (uint64_t e = 0; e < GeomElems(g); ++e)
      r.vals[size_t(e)] = GetF32(v + e * 4);
  }
  out->swap(recs);  // adopt only on full success
  return ParseResult::kOk;
}

// --------------------------------------------------------- slot store
// Rank 28: strictly under kv.pool (25) — KvPool spill/restore paths
// call in while holding the pool lock — and above nothing (SpillFile
// never calls out, so no lock ever nests inside kv.spill).
PTPU_LOCK_CLASS(kLockKvSpill, "kv.spill", 28);

class SpillFile {
 public:
  struct Stats {
    bool attached = false;
    uint64_t slots_total = 0, slots_in_use = 0, bytes_mapped = 0;
    uint64_t writes = 0, reads = 0, header_rejects = 0, exhausted = 0;
  };

  SpillFile() = default;
  SpillFile(const SpillFile&) = delete;
  SpillFile& operator=(const SpillFile&) = delete;
  ~SpillFile() { Detach(); }

  // Open-or-create the slot store at `path`. A pre-existing file is
  // ALWAYS reset (slot content is per-process scratch) but a
  // malformed pre-existing header is counted first — detection over
  // silent overwrite. max_bytes==0 means unbounded.
  bool Attach(const std::string& path, uint64_t max_bytes,
              const SpillGeom& geom, std::string* err) {
    ptpu::MutexLock l(mu_);
    if (fd_ >= 0) {
      *err = "spill: already attached to " + path_;
      return false;
    }
    if (!GeomValid(geom)) {
      *err = "spill: invalid geometry";
      return false;
    }
    const int fd = ::open(path.c_str(), O_RDWR | O_CREAT | O_CLOEXEC,
                          0600);
    if (fd < 0) {
      *err = "spill: cannot open " + path;
      return false;
    }
    uint8_t hdr[kSpillHeaderBytes];
    const ssize_t got = ::pread(fd, hdr, sizeof hdr, 0);
    if (got > 0) {
      SpillGeom old;
      if (ParseSpillHeader(hdr, size_t(got), &old) != ParseResult::kOk)
        ++header_rejects_;
    }
    uint8_t fresh[kSpillHeaderBytes];
    SerializeSpillHeader(geom, fresh);
    if (::ftruncate(fd, off_t(kSpillHeaderReserve)) != 0 ||
        ::pwrite(fd, fresh, sizeof fresh, 0) !=
            ssize_t(sizeof fresh)) {
      ::close(fd);
      *err = "spill: cannot initialize " + path;
      return false;
    }
    fd_ = fd;
    path_ = path;
    geom_ = geom;
    max_bytes_ = max_bytes;
    // chunk size rounded UP to a page multiple so every chunk's file
    // offset stays mmap-alignable as the file grows
    chunk_bytes_ = uint64_t(kSpillChunkSlots) * geom_.slot_bytes;
    chunk_bytes_ = (chunk_bytes_ + kSpillHeaderReserve - 1) /
                   kSpillHeaderReserve * kSpillHeaderReserve;
    return true;
  }

  bool attached() const {
    ptpu::MutexLock l(mu_);
    return fd_ >= 0;
  }

  // -1 when the store is detached, the byte cap is reached, or the
  // filesystem refuses growth — the caller surfaces all three as the
  // soft retryable "kv spill exhausted" error.
  int64_t Alloc() {
    ptpu::MutexLock l(mu_);
    if (fd_ < 0) return -1;
    if (free_.empty()) {
      const uint64_t grown =
          kSpillHeaderReserve + (chunks_.size() + 1) * chunk_bytes_;
      if (max_bytes_ > 0 && grown > max_bytes_) {
        ++exhausted_;
        return -1;
      }
      if (::ftruncate(fd_, off_t(grown)) != 0) {
        ++exhausted_;
        return -1;
      }
      void* m = ::mmap(nullptr, size_t(chunk_bytes_),
                       PROT_READ | PROT_WRITE, MAP_SHARED, fd_,
                       off_t(kSpillHeaderReserve +
                             chunks_.size() * chunk_bytes_));
      if (m == MAP_FAILED) {
        ++exhausted_;
        return -1;
      }
      const int64_t base = int64_t(chunks_.size()) * kSpillChunkSlots;
      chunks_.push_back(static_cast<uint8_t*>(m));
      for (int64_t s = base + kSpillChunkSlots; s-- > base;)
        free_.push_back(s);
    }
    const int64_t slot = free_.back();
    free_.pop_back();
    return slot;
  }

  void Free(int64_t slot) {
    ptpu::MutexLock l(mu_);
    if (slot < 0 || slot >= int64_t(chunks_.size()) * kSpillChunkSlots)
      return;
    free_.push_back(slot);
  }

  bool Write(int64_t slot, const float* src, size_t n) {
    ptpu::MutexLock l(mu_);
    uint8_t* p = slot_ptr_locked(slot, n);
    if (p == nullptr) return false;
    std::memcpy(p, src, n * sizeof(float));
    ++writes_;
    drop_slot_pages_locked(slot);
    return true;
  }

  bool Read(int64_t slot, float* dst, size_t n) {
    ptpu::MutexLock l(mu_);
    uint8_t* p = slot_ptr_locked(slot, n);
    if (p == nullptr) return false;
    std::memcpy(dst, p, n * sizeof(float));
    ++reads_;
    drop_slot_pages_locked(slot);
    return true;
  }

  // munmap + close; the file itself is LEFT on disk (per-machine
  // scratch, safe to delete any time — see MIGRATION.md)
  void Detach() {
    ptpu::MutexLock l(mu_);
    for (uint8_t* m : chunks_) ::munmap(m, size_t(chunk_bytes_));
    chunks_.clear();
    free_.clear();
    if (fd_ >= 0) ::close(fd_);
    fd_ = -1;
    path_.clear();
  }

  Stats Snapshot() const {
    ptpu::MutexLock l(mu_);
    Stats st;
    st.attached = fd_ >= 0;
    st.slots_total = chunks_.size() * uint64_t(kSpillChunkSlots);
    st.slots_in_use = st.slots_total - free_.size();
    st.bytes_mapped = chunks_.size() * chunk_bytes_;
    st.writes = writes_;
    st.reads = reads_;
    st.header_rejects = header_rejects_;
    st.exhausted = exhausted_;
    return st;
  }

 private:
  // Dirty MAP_SHARED pages count against this process's RSS until
  // writeback, and the whole point of the spill tier is to BOUND
  // resident memory — so after every slot copy the covering pages are
  // dropped back to the page cache.  MADV_DONTNEED on a shared file
  // mapping never loses data (the mapped pages ARE the page cache;
  // a later access merely re-faults them in), and neighbouring slots
  // sharing an edge page pay only that re-fault.  chunk_bytes_ is a
  // page multiple, so the rounded-up end never leaves the mapping.
  void drop_slot_pages_locked(int64_t slot) {
    static const uintptr_t kPg = uintptr_t(::sysconf(_SC_PAGESIZE));
    uint8_t* chunk = chunks_[size_t(slot / kSpillChunkSlots)];
    const uint64_t off =
        uint64_t(slot % kSpillChunkSlots) * geom_.slot_bytes;
    const uintptr_t beg = (uintptr_t(chunk) + off) & ~(kPg - 1);
    const uintptr_t end =
        (uintptr_t(chunk) + off + geom_.slot_bytes + kPg - 1) &
        ~(kPg - 1);
    ::madvise(reinterpret_cast<void*>(beg), size_t(end - beg),
              MADV_DONTNEED);
  }

  uint8_t* slot_ptr_locked(int64_t slot, size_t n) {
    if (fd_ < 0 || slot < 0 ||
        slot >= int64_t(chunks_.size()) * kSpillChunkSlots ||
        n * sizeof(float) > geom_.slot_bytes)
      return nullptr;
    return chunks_[size_t(slot / kSpillChunkSlots)] +
           uint64_t(slot % kSpillChunkSlots) * geom_.slot_bytes;
  }

  int fd_ = -1;
  std::string path_;
  SpillGeom geom_;
  uint64_t max_bytes_ = 0;
  uint64_t chunk_bytes_ = 0;
  std::vector<uint8_t*> chunks_;
  std::vector<int64_t> free_;
  uint64_t writes_ = 0, reads_ = 0, header_rejects_ = 0, exhausted_ = 0;
  mutable ptpu::Mutex mu_{kLockKvSpill};
};

}  // namespace spill
}  // namespace ptpu

#endif  // PTPU_SPILL_H_
