// C-hosted PS data-plane server — the wire half of the native PS hot
// path (csrc/ptpu_ps_table.cc holds the storage half).
//
// Reference counterpart: the brpc service loop of
// distributed/service/brpc_ps_server.cc — request parsing, the table
// gather/scatter, and the reply write all happen in C++ worker
// threads; Python never touches a hot frame. The Python TableService
// keeps the CONTROL plane (kv store, barriers, shuffle, heter calls)
// on its multiprocessing.connection listener and advertises this
// data-plane port for pull/push only.
//
// Protocol (mirrors distributed/ps/wire.py fast frames):
//   * connect: server sends a 16-byte random nonce; the client answers
//     with one frame containing HMAC-SHA256(authkey, nonce); server
//     replies one byte 0x01 and the session is open (the
//     multiprocessing.connection HMAC challenge, restated for a C peer
//     that cannot speak Python's banner format).
//   * frames: u32-LE length prefix + payload in BOTH directions. The
//     payload is exactly a wire.py fast frame: version byte, tag byte
//     (0x50 PULL_REQ / 0x52 PUSH_REQ in; 0x51 PULL_REP / 0x53 OK /
//     0x54 ERR out), fixed little-endian layout.
//   * pull replies are gathered straight into the connection's reused
//     reply buffer — zero per-frame allocation in steady state.
//
// Concurrency: one detached-joinable thread per accepted connection
// (the brpc worker-pool analogue): a slow client stalls only its own
// socket. Table access synchronizes inside ptpu_ps_table.cc (shared
// lock pulls / exclusive pushes).

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

#include "ptpu_hmac.h"
#include "ptpu_ps_table.h"
#include "ptpu_stats.h"
#include "ptpu_wire.h"

namespace {

// SHA-256 + HMAC live in the shared csrc/ptpu_hmac.h (the serving
// runtime's handshake uses the same MAC).
using ptpu::HmacSha256;
using ptpu::Sha256;

// ---------------------------------------------------------------------------
// Frame constants — keep in sync with distributed/ps/wire.py.
// ---------------------------------------------------------------------------

constexpr uint8_t kWireVersion = 1;
constexpr uint8_t kTagPullReq = 0x50;
constexpr uint8_t kTagPullRep = 0x51;
constexpr uint8_t kTagPushReq = 0x52;
constexpr uint8_t kTagOk = 0x53;
constexpr uint8_t kTagErr = 0x54;
constexpr uint32_t kMaxFrame = 1u << 30;

// exact socket I/O lives in the shared csrc/ptpu_wire.h
using ptpu::ReadExact;
using ptpu::WriteExact;

// Wire-level counters for one exposed table (ptpu_stats.h relaxed
// atomics; storage-level counters live inside the table itself).
struct TableWireStats {
  ptpu::Counter pull_ops, pull_rows, push_ops, push_rows, bytes_in,
      bytes_out;

  void Reset() {
    pull_ops.Reset();
    pull_rows.Reset();
    push_ops.Reset();
    push_rows.Reset();
    bytes_in.Reset();
    bytes_out.Reset();
  }
};

// Server-global wire counters + serve-latency histograms. Always-on:
// a handful of relaxed fetch_adds and two NowUs reads per frame —
// noise against the frame's own syscalls (bench-verified <3% on the
// pipelined pull phase).
struct ServerStats {
  ptpu::Counter pull_ops, pull_rows, push_ops, push_rows, bytes_in,
      bytes_out, err_frames, proto_errors, handshake_fails,
      conns_accepted;
  std::atomic<int64_t> conns_active{0};
  ptpu::Histogram pull_us, push_us;  // frame-read -> reply-written

  void Reset() {
    pull_ops.Reset();
    pull_rows.Reset();
    push_ops.Reset();
    push_rows.Reset();
    bytes_in.Reset();
    bytes_out.Reset();
    err_frames.Reset();
    proto_errors.Reset();
    handshake_fails.Reset();
    conns_accepted.Reset();
    pull_us.Reset();
    push_us.Reset();
  }
};

struct ShardEntry {
  void *table;
  int64_t lo;  // global-id offset of this shard's first row
  TableWireStats *wire;  // owned by PsServer::table_stats
};

struct PsServer {
  int listen_fd = -1;
  int port = 0;
  std::string authkey;
  std::atomic<bool> stop{false};
  std::thread accept_thread;
  std::mutex mu;  // guards tables + conn bookkeeping
  std::map<std::string, ShardEntry> tables;
  // per-table wire stats: pointers are handed to ShardEntry copies, so
  // entries are never erased (re-register reuses the slot)
  std::map<std::string, std::unique_ptr<TableWireStats>> table_stats;
  ServerStats stats;
  std::vector<int> conn_fds;
  std::vector<std::thread> conn_threads;
  std::vector<std::thread::id> done_threads;  // finished, join pending

  ~PsServer() { Stop(); }

  void Stop() {
    if (stop.exchange(true)) return;
    // shutdown() wakes the blocked accept() (EINVAL) but keeps the fd
    // alive; closing or clearing listen_fd BEFORE the join would race
    // the accept thread's concurrent read of it (TSan-caught in the
    // serving twin of this loop) and invite fd-number reuse while
    // accept() still holds the old value
    if (listen_fd >= 0) ::shutdown(listen_fd, SHUT_RDWR);
    {
      std::lock_guard<std::mutex> g(mu);
      for (int fd : conn_fds) ::shutdown(fd, SHUT_RDWR);
    }
    if (accept_thread.joinable()) accept_thread.join();
    if (listen_fd >= 0) {
      ::close(listen_fd);
      listen_fd = -1;
    }
    std::vector<std::thread> ts;
    {
      std::lock_guard<std::mutex> g(mu);
      ts.swap(conn_threads);
    }
    for (auto &t : ts)
      if (t.joinable()) t.join();
    {
      std::lock_guard<std::mutex> g(mu);
      for (int fd : conn_fds) ::close(fd);
      conn_fds.clear();
    }
  }

  bool SendFrame(int fd, const uint8_t *payload, uint32_t n,
                 std::vector<uint8_t> *buf) {
    // one contiguous write: u32-LE length + payload (the payload is
    // already in *buf with 4 bytes of headroom when buf != null)
    if (buf) {
      (*buf)[0] = uint8_t(n);
      (*buf)[1] = uint8_t(n >> 8);
      (*buf)[2] = uint8_t(n >> 16);
      (*buf)[3] = uint8_t(n >> 24);
      return WriteExact(fd, buf->data(), size_t(n) + 4);
    }
    uint8_t hdr[4] = {uint8_t(n), uint8_t(n >> 8), uint8_t(n >> 16),
                      uint8_t(n >> 24)};
    return WriteExact(fd, hdr, 4) && WriteExact(fd, payload, n);
  }

  bool SendErr(int fd, const std::string &msg) {
    std::vector<uint8_t> f(4 + 2 + 4 + msg.size());
    f[4] = kWireVersion;
    f[5] = kTagErr;
    const uint32_t n = uint32_t(msg.size());
    f[6] = uint8_t(n);
    f[7] = uint8_t(n >> 8);
    f[8] = uint8_t(n >> 16);
    f[9] = uint8_t(n >> 24);
    std::memcpy(f.data() + 10, msg.data(), msg.size());
    stats.err_frames.Add(1);
    stats.bytes_out.Add(f.size());
    return SendFrame(fd, nullptr, uint32_t(f.size() - 4), &f);
  }


  void Serve(int fd) {
    std::vector<uint8_t> req;
    std::vector<uint8_t> rep;  // reused: [4B length][frame payload]
    std::vector<int64_t> local;
    if (!ptpu::ServerHandshake(fd, authkey)) {
      stats.handshake_fails.Add(1);
      return;
    }
    // drop-the-connection protocol errors are counted before the
    // return — the wire half of the Python plane's frame_errors
    const auto proto_err = [this]() { stats.proto_errors.Add(1); };
    for (;;) {
      uint8_t lenb[4];
      if (!ReadExact(fd, lenb, 4)) return;
      const uint32_t n = uint32_t(lenb[0]) | uint32_t(lenb[1]) << 8 |
                         uint32_t(lenb[2]) << 16 |
                         uint32_t(lenb[3]) << 24;
      if (n < 2 || n > kMaxFrame) return proto_err();
      if (req.size() < n) req.resize(n);
      if (!ReadExact(fd, req.data(), n)) return;
      const int64_t t0 = ptpu::NowUs();
      stats.bytes_in.Add(4 + uint64_t(n));
      if (req[0] != kWireVersion) return proto_err();
      const uint8_t tag = req[1];
      if (tag != kTagPullReq && tag != kTagPushReq) return proto_err();
      // [u8 tlen][table]
      if (n < 3) return proto_err();
      const uint8_t tlen = req[2];
      size_t off = 3 + tlen;
      if (n < off) return proto_err();
      const std::string table(reinterpret_cast<char *>(req.data() + 3),
                              tlen);
      ShardEntry entry;
      {
        std::lock_guard<std::mutex> g(mu);
        auto it = tables.find(table);
        if (it == tables.end()) {
          if (!SendErr(fd, "unknown table '" + table +
                               "' on data plane"))
            return;
          continue;
        }
        entry = it->second;
      }
      entry.wire->bytes_in.Add(4 + uint64_t(n));
      if (tag == kTagPullReq) {
        // [u32 n][n x i64 ids]
        if (n < off + 4) return proto_err();
        uint32_t cnt;
        std::memcpy(&cnt, req.data() + off, 4);
        off += 4;
        if (n != off + 8ull * cnt) return proto_err();
        // bound the REPLY like the request: a small ids frame must not
        // be able to demand a multi-GB gather allocation
        if (10 + size_t(cnt) * size_t(ptpu_ps_table_dim(entry.table)) *
                4 > kMaxFrame) {
          if (!SendErr(fd, "pull reply would exceed frame limit"))
            return;
          continue;
        }
        // ids sit at 7+tlen into the frame — any alignment; every
        // read goes through the unaligned-safe GetI64
        const uint8_t *ids_b = req.data() + off;
        const int64_t rows = ptpu_ps_table_rows(entry.table);
        const int64_t dim = ptpu_ps_table_dim(entry.table);
        const size_t row_b = size_t(dim) * 4;
        const size_t body = size_t(cnt) * row_b;
        // reply = length + header + gathered rows in the REUSED
        // per-connection buffer, shipped with one write. (A
        // row-pointer writev was tried first — 512 iovecs of 256B
        // cost more in per-segment kernel overhead than the one
        // 131KB gather memcpy saves.)
        if (rep.size() < 14 + body) rep.resize(14 + body);
        ptpu::PutU32(rep.data(), uint32_t(10 + body));
        const uint32_t flen = uint32_t(10 + body);
        rep[4] = kWireVersion;
        rep[5] = kTagPullRep;
        ptpu::PutU32(rep.data() + 6, cnt);
        ptpu::PutU32(rep.data() + 10, uint32_t(dim));
        const float *w = ptpu_ps_table_data(entry.table);
        // gather straight into the reply as BYTES: the f32 rows start
        // at +14, which is not 4-aligned, so a float* view would be UB
        uint8_t *out = rep.data() + 14;
        bool bad = false;
        ptpu_ps_table_rdlock(entry.table);
        for (uint32_t i = 0; i < cnt; ++i) {
          const int64_t id = ptpu::GetI64(ids_b + 8 * i) - entry.lo;
          if (id < 0 || id >= rows) {
            bad = true;
            break;
          }
          std::memcpy(out + size_t(i) * row_b, w + id * dim, row_b);
        }
        ptpu_ps_table_rdunlock(entry.table);
        if (bad) {
          if (!SendErr(fd, "pull id out of shard range")) return;
          continue;
        }
        if (!WriteExact(fd, rep.data(), 4 + size_t(flen))) return;
        ptpu_ps_table_note_pull(entry.table, int64_t(cnt));
        stats.pull_ops.Add(1);
        stats.pull_rows.Add(cnt);
        stats.bytes_out.Add(4 + uint64_t(flen));
        stats.pull_us.Observe(uint64_t(ptpu::NowUs() - t0));
        entry.wire->pull_ops.Add(1);
        entry.wire->pull_rows.Add(cnt);
        entry.wire->bytes_out.Add(4 + uint64_t(flen));
      } else {
        // [u8 flags][u32 n][u32 dim][ids][grads]
        if (n < off + 9) return proto_err();
        const bool is_async = req[off] != 0;
        (void)is_async;  // C applies inline — ack-after-apply is a
                         // strictly stronger contract than coalesce
        uint32_t cnt, d32;
        std::memcpy(&cnt, req.data() + off + 1, 4);
        std::memcpy(&d32, req.data() + off + 5, 4);
        off += 9;
        if (n != off + 8ull * cnt + 4ull * cnt * d32) return proto_err();
        const int64_t dim = ptpu_ps_table_dim(entry.table);
        const auto count_push = [&](uint32_t rows) {
          stats.push_ops.Add(1);
          stats.push_rows.Add(rows);
          stats.bytes_out.Add(6);  // 4B length + OK frame
          stats.push_us.Observe(uint64_t(ptpu::NowUs() - t0));
          entry.wire->push_ops.Add(1);
          entry.wire->push_rows.Add(rows);
          entry.wire->bytes_out.Add(6);
        };
        if (cnt == 0) {  // empty push (dim underivable): trivially ok
          if (rep.size() < 6) rep.resize(6);
          rep[4] = kWireVersion;
          rep[5] = kTagOk;
          if (!SendFrame(fd, nullptr, 2, &rep)) return;
          count_push(0);
          continue;
        }
        if (int64_t(d32) != dim) {
          // application error, not a protocol error: the frame parsed
          // fine — answer like the Python plane instead of hanging up
          if (!SendErr(fd, "push dim " + std::to_string(d32) +
                               " != table dim " + std::to_string(dim)))
            return;
          continue;
        }
        // ids/grads sit at arbitrary offsets (table-name length shifts
        // them): ids are read via the unaligned-safe GetI64; grads are
        // handed to the table as a BYTE pointer — ptpu_ps_table_push
        // reads each f32 with memcpy, so no aligned copy is needed
        const uint8_t *ids_b = req.data() + off;
        const uint8_t *grads_b = req.data() + off + 8ull * cnt;
        if (local.size() < cnt) local.resize(cnt);
        for (uint32_t i = 0; i < cnt; ++i)
          local[i] = ptpu::GetI64(ids_b + 8 * i) - entry.lo;
        if (ptpu_ps_table_push_raw(entry.table, local.data(), cnt,
                                   grads_b) != 0) {
          if (!SendErr(fd, ptpu_ps_last_error())) return;
          continue;
        }
        if (rep.size() < 6) rep.resize(6);
        rep[4] = kWireVersion;
        rep[5] = kTagOk;
        if (!SendFrame(fd, nullptr, 2, &rep)) return;
        count_push(cnt);
      }
    }
  }

  // Join threads whose connections have closed — without this, a
  // long-lived server under connection churn (one Channel per client
  // phase) accumulates zombie std::threads until Stop().
  void ReapFinished() {
    std::vector<std::thread> reap;
    {
      std::lock_guard<std::mutex> g(mu);
      if (done_threads.empty()) return;
      for (auto it = conn_threads.begin(); it != conn_threads.end();) {
        const auto tid = it->get_id();
        if (std::find(done_threads.begin(), done_threads.end(), tid) !=
            done_threads.end()) {
          reap.push_back(std::move(*it));
          it = conn_threads.erase(it);
        } else {
          ++it;
        }
      }
      done_threads.clear();
    }
    for (auto &t : reap)
      if (t.joinable()) t.join();
  }

  void AcceptLoop() {
    for (;;) {
      const int fd = ::accept(listen_fd, nullptr, nullptr);
      if (fd < 0) {
        // transient accept failures (peer RST, EINTR, momentary fd
        // exhaustion) must not stop the server from accepting; only
        // the Stop()-closed listener ends the loop
        if (!stop.load() && ptpu::AcceptErrnoIsTransient(errno)) {
          std::this_thread::sleep_for(std::chrono::milliseconds(5));
          continue;
        }
        return;
      }
      if (stop.load()) {
        ::close(fd);
        return;
      }
      ReapFinished();
      stats.conns_accepted.Add(1);
      const int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      // deep pipelines keep several MB in flight per connection; a
      // large send buffer keeps the reply writes from stalling
      const int buf = 4 << 20;
      ::setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &buf, sizeof(buf));
      ::setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &buf, sizeof(buf));
      std::lock_guard<std::mutex> g(mu);
      conn_fds.push_back(fd);
      conn_threads.emplace_back([this, fd]() {
        // an escaping exception (e.g. bad_alloc on a hostile frame)
        // would std::terminate the whole process — contain it to this
        // connection, like the Python plane's drop-on-malformed
        stats.conns_active.fetch_add(1, std::memory_order_relaxed);
        try {
          Serve(fd);
        } catch (...) {
        }
        stats.conns_active.fetch_sub(1, std::memory_order_relaxed);
        {
          // prune BEFORE close: once closed, the OS may reuse the fd
          // number and Stop() must not shutdown an unrelated socket
          std::lock_guard<std::mutex> g2(mu);
          conn_fds.erase(
              std::remove(conn_fds.begin(), conn_fds.end(), fd),
              conn_fds.end());
          done_threads.push_back(std::this_thread::get_id());
        }
        ::close(fd);
      });
    }
  }
};

thread_local std::string g_srv_error;

}  // namespace

PTPU_PS_EXPORT const char *ptpu_ps_server_last_error(void) {
  return g_srv_error.c_str();
}

// Start the data-plane server on `port` (0 picks a free one;
// ptpu_ps_server_port reports it). `loopback_only` nonzero binds
// 127.0.0.1 — single-host jobs must not expose pull/push to the
// network (the Python control plane makes the same choice). Returns
// NULL on error.
PTPU_PS_EXPORT void *ptpu_ps_server_start(int port, const char *authkey,
                                          int authkey_len,
                                          int loopback_only) {
  auto *s = new PsServer();
  if (authkey && authkey_len > 0)
    s->authkey.assign(authkey, size_t(authkey_len));
  s->listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (s->listen_fd < 0) {
    g_srv_error = "ptpu_ps_server_start: socket() failed";
    delete s;
    return nullptr;
  }
  const int one = 1;
  ::setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one,
               sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr =
      htonl(loopback_only ? INADDR_LOOPBACK : INADDR_ANY);
  addr.sin_port = htons(uint16_t(port));
  if (::bind(s->listen_fd, reinterpret_cast<sockaddr *>(&addr),
             sizeof(addr)) != 0 ||
      ::listen(s->listen_fd, 64) != 0) {
    g_srv_error = "ptpu_ps_server_start: bind/listen on port " +
                  std::to_string(port) + " failed";
    ::close(s->listen_fd);
    s->listen_fd = -1;
    delete s;
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  ::getsockname(s->listen_fd, reinterpret_cast<sockaddr *>(&addr),
                &alen);
  s->port = int(ntohs(addr.sin_port));
  s->accept_thread = std::thread([s]() { s->AcceptLoop(); });
  return s;
}

// Handle-taking entries guard NULL like the table ABI: defined error
// returns beat segfaults when a binding races teardown.
PTPU_PS_EXPORT int ptpu_ps_server_port(void *h) {
  auto *s = static_cast<PsServer *>(h);
  return s ? s->port : -1;
}

// Expose `table` (a ptpu_ps_table handle) as `name` with global-id
// offset `lo` — the server subtracts lo before the bounds-checked
// local gather/scatter.
PTPU_PS_EXPORT int ptpu_ps_server_register(void *h, const char *name,
                                           void *table, int64_t lo) {
  auto *s = static_cast<PsServer *>(h);
  if (!s || !name || !table) {
    g_srv_error = "ptpu_ps_server_register: null handle or table";
    return -1;
  }
  std::lock_guard<std::mutex> g(s->mu);
  auto &ws = s->table_stats[name];
  if (!ws) ws.reset(new TableWireStats());
  s->tables[name] = ShardEntry{table, lo, ws.get()};
  return 0;
}

// JSON snapshot: {"server":{global wire counters + pull_us/push_us
// histograms}, "tables":{name:{"wire":{...},"table":{storage counters
// from ptpu_ps_table_stats_json}}}}. Returned pointer is a
// thread-local render buffer, valid until the calling thread's next
// ptpu_ps_server_stats_json call.
PTPU_PS_EXPORT const char *ptpu_ps_server_stats_json(void *h) {
  thread_local std::string g_json;
  auto *s = static_cast<PsServer *>(h);
  if (!s) return "{}";
  std::string out = "{\"server\":{";
  const ServerStats &st = s->stats;
  const struct { const char *name; const ptpu::Counter *c; } cs[] = {
      {"pull_ops", &st.pull_ops},       {"pull_rows", &st.pull_rows},
      {"push_ops", &st.push_ops},       {"push_rows", &st.push_rows},
      {"bytes_in", &st.bytes_in},       {"bytes_out", &st.bytes_out},
      {"err_frames", &st.err_frames},   {"proto_errors", &st.proto_errors},
      {"handshake_fails", &st.handshake_fails},
      {"conns_accepted", &st.conns_accepted},
  };
  for (const auto &kv : cs) {
    ptpu::AppendJsonU64(&out, kv.name, kv.c->Get());
    out += ',';
  }
  ptpu::AppendJsonU64(&out, "conns_active",
                      uint64_t(st.conns_active.load(
                          std::memory_order_relaxed)));
  out += ',';
  ptpu::AppendJsonHist(&out, "pull_us", st.pull_us);
  out += ',';
  ptpu::AppendJsonHist(&out, "push_us", st.push_us);
  out += "},\"tables\":{";
  {
    std::lock_guard<std::mutex> g(s->mu);
    bool first = true;
    for (const auto &kv : s->tables) {
      if (!first) out += ',';
      first = false;
      out += '"';
      out += ptpu::JsonEscape(kv.first);
      out += "\":{\"wire\":{";
      const TableWireStats &w = *kv.second.wire;
      const struct { const char *name; const ptpu::Counter *c; } ws[] = {
          {"pull_ops", &w.pull_ops},   {"pull_rows", &w.pull_rows},
          {"push_ops", &w.push_ops},   {"push_rows", &w.push_rows},
          {"bytes_in", &w.bytes_in},   {"bytes_out", &w.bytes_out},
      };
      bool wfirst = true;
      for (const auto &c : ws) {
        if (!wfirst) out += ',';
        wfirst = false;
        ptpu::AppendJsonU64(&out, c.name, c.c->Get());
      }
      out += "},\"table\":";
      out += ptpu_ps_table_stats_json(kv.second.table);
      out += '}';
    }
  }
  out += "}}";
  g_json.swap(out);
  return g_json.c_str();
}

// Reset wire counters (global + per-table) AND the storage counters of
// every registered table — one call zeroes the whole serving view.
PTPU_PS_EXPORT void ptpu_ps_server_stats_reset(void *h) {
  auto *s = static_cast<PsServer *>(h);
  if (!s) return;
  s->stats.Reset();
  std::lock_guard<std::mutex> g(s->mu);
  for (auto &kv : s->tables) {
    kv.second.wire->Reset();
    ptpu_ps_table_stats_reset(kv.second.table);
  }
}

PTPU_PS_EXPORT void ptpu_ps_server_stop(void *h) {
  auto *s = static_cast<PsServer *>(h);
  if (!s) return;
  s->Stop();
  delete s;
}
