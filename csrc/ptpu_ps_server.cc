// C-hosted PS data-plane server — the wire half of the native PS hot
// path (csrc/ptpu_ps_table.cc holds the storage half).
//
// Reference counterpart: the brpc service loop of
// distributed/service/brpc_ps_server.cc — request parsing, the table
// gather/scatter, and the reply write all happen in C++ event threads;
// Python never touches a hot frame. The Python TableService keeps the
// CONTROL plane (kv store, barriers, shuffle, heter calls) on its
// multiprocessing.connection listener and advertises this data-plane
// port for pull/push only.
//
// Protocol (mirrors distributed/ps/wire.py fast frames):
//   * connect: server sends a 16-byte random nonce; the client answers
//     with one frame containing HMAC-SHA256(authkey, nonce); server
//     replies one byte 0x01 and the session is open (the
//     multiprocessing.connection HMAC challenge, restated for a C peer
//     that cannot speak Python's banner format).
//   * frames: u32-LE length prefix + payload in BOTH directions. The
//     payload is exactly a wire.py fast frame: version byte, tag byte
//     (0x50 PULL_REQ / 0x52 PUSH_REQ in; 0x51 PULL_REP / 0x53 OK /
//     0x54 ERR out), fixed little-endian layout.
//   * pull replies are gathered straight into a pooled per-connection
//     reply buffer — zero per-frame allocation in steady state.
//
// Concurrency: the shared epoll event core (csrc/ptpu_net.{h,cc}) —
// 1 acceptor + N event threads; frame handlers run INLINE on the
// event threads (a table gather is microseconds, never worth a hop).
// Table access synchronizes inside ptpu_ps_table.cc (shared lock
// pulls / exclusive pushes). The old thread-per-connection loop is
// gone: thousands of idle or slow clients now cost file descriptors,
// not threads (tools/ptpu_check.py's `net` checker keeps it that way).

#include <algorithm>
#include <atomic>
#include <cstdlib>
#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <string>
#include <vector>

#include "ptpu_invar.h"
#include "ptpu_net.h"
#include "ptpu_ps_table.h"
#include "ptpu_stats.h"
#include "ptpu_trace.h"
#include "ptpu_wire.h"

namespace {

// ---------------------------------------------------------------------------
// Frame constants — keep in sync with distributed/ps/wire.py.
// ---------------------------------------------------------------------------

// Registry lock of the data-plane server (rank table: README
// "Correctness tooling"): nests OUTSIDE the per-table storage lock
// (StatsJson renders table stats under it) and outside reply sends.
PTPU_LOCK_CLASS(kLockPsRegistry, "ps.registry", 40);

constexpr uint8_t kWireVersion = 1;
// Traced frames (ISSUE 10): [ver=2][tag][u64 trace id] then the v1
// body; replies to a traced request echo the same extension. Old v1
// clients are untouched. Python twin: wire.py WIRE_VERSION_TRACED.
constexpr uint8_t kWireVersionTraced = 2;
constexpr uint8_t kTagPullReq = 0x50;
constexpr uint8_t kTagPullRep = 0x51;
constexpr uint8_t kTagPushReq = 0x52;
constexpr uint8_t kTagOk = 0x53;
constexpr uint8_t kTagErr = 0x54;
constexpr uint32_t kMaxFrame = 1u << 30;

// Wire-level counters for one exposed table (ptpu_stats.h relaxed
// atomics; storage-level counters live inside the table itself).
struct TableWireStats {
  ptpu::Counter pull_ops, pull_rows, push_ops, push_rows, bytes_in,
      bytes_out;

  void Reset() {
    pull_ops.Reset();
    pull_rows.Reset();
    push_ops.Reset();
    push_rows.Reset();
    bytes_in.Reset();
    bytes_out.Reset();
  }
};

// Server-global wire counters + serve-latency histograms. Always-on:
// a handful of relaxed fetch_adds and two NowUs reads per frame —
// noise against the frame's own syscalls (bench-verified <3% on the
// pipelined pull phase). Connection-lifecycle counters (accepts,
// sheds, handshake outcomes, active gauge) live in the embedded
// net-core stats block and render under the same "server" object.
struct ServerStats {
  ptpu::Counter pull_ops, pull_rows, push_ops, push_rows, bytes_in,
      bytes_out, err_frames, proto_errors;
  // CPU microseconds the event threads burned inside OnFrame
  // (ThreadCpuUs deltas, ISSUE 17): cpu_us / (pull_ops + push_ops)
  // is the PS bench's cycles-per-request column.
  ptpu::Counter cpu_us;
  ptpu::Histogram pull_us, push_us;  // frame-read -> reply-queued

  void Reset() {
    cpu_us.Reset();
    pull_ops.Reset();
    pull_rows.Reset();
    push_ops.Reset();
    push_rows.Reset();
    bytes_in.Reset();
    bytes_out.Reset();
    err_frames.Reset();
    proto_errors.Reset();
    pull_us.Reset();
    push_us.Reset();
  }
};

struct ShardEntry {
  void *table;
  int64_t lo;  // global-id offset of this shard's first row
  TableWireStats *wire;  // owned by PsServer::table_stats
};

struct PsServer {
  std::string authkey;
  int port = 0;
  ptpu::Mutex mu{kLockPsRegistry};  // guards tables
  std::map<std::string, ShardEntry> tables;
  // per-table wire stats: pointers are handed to ShardEntry copies, so
  // entries are never erased (re-register reuses the slot)
  std::map<std::string, std::unique_ptr<TableWireStats>> table_stats;
  ServerStats stats;
  ptpu::net::Stats net;
  std::unique_ptr<ptpu::net::Server> net_srv;

  ~PsServer() { Stop(); }

  bool Start(int want_port, int loopback_only, int http_port,
             std::string *err) {
    ptpu::net::Options opt;
    opt.port = want_port;
    opt.loopback_only = loopback_only != 0;
    opt.authkey = authkey;
    opt.max_frame = kMaxFrame;
    opt.http_port = http_port;
    opt = ptpu::net::OptionsFromEnv(opt);
    ptpu::net::Callbacks cbs;
    cbs.on_frame = [this](const ptpu::net::ConnPtr &c,
                          const uint8_t *p, uint32_t n) {
      return OnFrame(c, p, n);
    };
    cbs.on_oversize = [this](const ptpu::net::ConnPtr &) {
      stats.proto_errors.Add(1);
    };
    cbs.on_http = [this](const std::string &target) {
      return HandleHttp(target);
    };
    net_srv.reset(new ptpu::net::Server(opt, std::move(cbs), &net));
    if (!net_srv->Start(err)) {
      net_srv.reset();
      return false;
    }
    port = net_srv->port();
    return true;
  }

  // Telemetry endpoints, served inline on the event threads from the
  // second (HTTP) listener: the brpc /vars-/rpcz-style surface
  // (shared routes — csrc/ptpu_net.cc TelemetryHttp).
  ptpu::net::HttpReply HandleHttp(const std::string &target) {
    const std::string path = target.substr(0, target.find('?'));
    if (path == "/invarz") {
      // conservation-law report (ISSUE 20) — authoritative at
      // quiesce, informational while pulls/pushes are in flight
      ptpu::net::HttpReply rep;
      rep.content_type = "application/json";
      rep.body = ptpu::invar::CheckJson(StatsJson(), "ps");
      rep.body += '\n';
      return rep;
    }
    return ptpu::net::TelemetryHttp(
        target, [this] { return StatsJson(); }, "ptpu_ps",
        /*draining=*/false);
  }

  std::string StatsJson();

  void Stop() {
    if (!net_srv) return;
    // graceful drain: stop accepting, flush queued replies, close
    net_srv->Stop();
    net_srv.reset();
    // conservation-law gate (ISSUE 20): drained == quiescent — the
    // point where every `==` law must hold exactly
    ptpu::invar::GateQuiesced(StatsJson(), "ps", "ps.Stop");
  }

  bool SendErr(const ptpu::net::ConnPtr &conn, const std::string &msg) {
    std::vector<uint8_t> f = conn->AcquireBuf();
    f.resize(4 + 2 + 4 + msg.size());
    f[4] = kWireVersion;
    f[5] = kTagErr;
    ptpu::PutU32(f.data() + 6, uint32_t(msg.size()));
    std::memcpy(f.data() + 10, msg.data(), msg.size());
    stats.err_frames.Add(1);
    stats.bytes_out.Add(f.size());
    return conn->SendPayload(std::move(f));
  }

  // One complete framed request, dispatched inline on an event
  // thread. kClose on protocol violations (the old loop hung up the
  // same way); application errors answer ERR frames and keep going.
  // v2 frames carry [u64 trace id] between [ver][tag] and the v1
  // body; REP/OK replies to a traced request echo it (ERR frames stay
  // v1 — error paths are never latency-traced).
  ptpu::net::FrameResult OnFrame(const ptpu::net::ConnPtr &conn,
                                 const uint8_t *req, uint32_t n) {
    using ptpu::net::FrameResult;
    // scope-aggregate this frame's event-thread CPU into cpu_us
    // (cycles-per-request telemetry, ISSUE 17)
    struct CpuScope {
      ptpu::Counter *c;
      int64_t t0;
      ~CpuScope() { c->Add(uint64_t(ptpu::ThreadCpuUs() - t0)); }
    } cpu{&stats.cpu_us, ptpu::ThreadCpuUs()};
    const auto proto_err = [this]() {
      stats.proto_errors.Add(1);
      return FrameResult::kClose;
    };
    if (n < 2) return proto_err();
    const int64_t t0 = ptpu::NowUs();
    stats.bytes_in.Add(4 + uint64_t(n));
    uint64_t wire_tid = 0;
    uint32_t ext = 0;
    if (req[0] == kWireVersionTraced) {
      if (n < 2 + ptpu::trace::kTraceExt) return proto_err();
      wire_tid = ptpu::GetU64(req + 2);  // trace id at payload +2
      ext = ptpu::trace::kTraceExt;
    } else if (req[0] != kWireVersion) {
      return proto_err();
    }
    const uint8_t tag = req[1];
    if (tag != kTagPullReq && tag != kTagPushReq) return proto_err();
    // sampling decision (one relaxed load when tracing is off); a
    // client-sent trace id is always traced while tracing is on
    const uint64_t tid = ptpu::trace::Global().BeginRequest(wire_tid);
    const int64_t t_read =
        conn->frame_recv_us() > 0 ? conn->frame_recv_us() : t0;
    // [u8 tlen][table]
    if (n < 3 + ext) return proto_err();
    const uint8_t tlen = req[2 + ext];
    size_t off = 3 + ext + tlen;
    if (n < off) return proto_err();
    const std::string table(
        reinterpret_cast<const char *>(req + 3 + ext), tlen);
    ShardEntry entry;
    {
      ptpu::MutexLock g(mu);
      auto it = tables.find(table);
      if (it == tables.end()) {
        if (!SendErr(conn, "unknown table '" + table +
                               "' on data plane"))
          return FrameResult::kClose;
        return FrameResult::kOk;
      }
      entry = it->second;
    }
    entry.wire->bytes_in.Add(4 + uint64_t(n));
    if (tag == kTagPullReq) {
      // [u32 n][n x i64 ids]
      if (n < off + 4) return proto_err();
      uint32_t cnt;
      std::memcpy(&cnt, req + off, 4);
      off += 4;
      if (n != off + 8ull * cnt) return proto_err();
      // bound the REPLY like the request: a small ids frame must not
      // be able to demand a multi-GB gather allocation
      if (10 + size_t(cnt) * size_t(ptpu_ps_table_dim(entry.table)) *
              4 > kMaxFrame) {
        if (!SendErr(conn, "pull reply would exceed frame limit"))
          return FrameResult::kClose;
        return FrameResult::kOk;
      }
      // ids sit at 7+tlen into the frame — any alignment; every
      // read goes through the unaligned-safe GetI64
      const uint8_t *ids_b = req + off;
      const int64_t rows = ptpu_ps_table_rows(entry.table);
      const int64_t dim = ptpu_ps_table_dim(entry.table);
      const size_t row_b = size_t(dim) * 4;
      const size_t body = size_t(cnt) * row_b;
      // reply = length + header + gathered rows in a POOLED
      // per-connection buffer, queued for one writev flush. (A
      // row-pointer writev was tried first — 512 iovecs of 256B cost
      // more in per-segment kernel overhead than the one 131KB
      // gather memcpy saves.) A traced request's reply echoes the
      // trace id: header grows by ho == kTraceExt bytes after the tag.
      const size_t ho = wire_tid ? size_t(ptpu::trace::kTraceExt) : 0;
      std::vector<uint8_t> rep = conn->AcquireBuf();
      rep.resize(14 + ho + body);
      ptpu::PutU32(rep.data(), uint32_t(10 + ho + body));
      const uint32_t flen = uint32_t(10 + ho + body);
      rep[4] = wire_tid ? kWireVersionTraced : kWireVersion;
      rep[5] = kTagPullRep;
      if (wire_tid) ptpu::PutU64(rep.data() + 6, wire_tid);
      ptpu::PutU32(rep.data() + 6 + ho, cnt);
      ptpu::PutU32(rep.data() + 10 + ho, uint32_t(dim));
      const float *w = ptpu_ps_table_data(entry.table);
      // gather straight into the reply as BYTES: the f32 rows start
      // at +14(+ho), which is not 4-aligned, so a float* view is UB
      uint8_t *out = rep.data() + 14 + ho;
      bool bad = false;
      ptpu_ps_table_rdlock(entry.table);
      for (uint32_t i = 0; i < cnt; ++i) {
        // id arithmetic in uint64 space: a hostile id near INT64_MIN
        // minus a shard offset must WRAP (defined) and fail the range
        // check below — as signed math it is UB and aborts a
        // fail-fast build on one frame (fuzzing finding, ISSUE 11;
        // repro: corpus/wire_ps/crash-pull-id-underflow.bin)
        const int64_t id = int64_t(
            uint64_t(ptpu::GetI64(ids_b + 8 * i)) - uint64_t(entry.lo));
        if (id < 0 || id >= rows) {
          bad = true;
          break;
        }
        std::memcpy(out + size_t(i) * row_b, w + id * dim, row_b);
      }
      ptpu_ps_table_rdunlock(entry.table);
      if (bad) {
        if (!SendErr(conn, "pull id out of shard range"))
          return FrameResult::kClose;
        return FrameResult::kOk;
      }
      if (!conn->SendPayload(std::move(rep), tid, cnt))
        return FrameResult::kClose;
      ptpu_ps_table_note_pull(entry.table, int64_t(cnt));
      stats.pull_ops.Add(1);
      stats.pull_rows.Add(cnt);
      stats.bytes_out.Add(4 + uint64_t(flen));
      const int64_t t1 = ptpu::NowUs();
      stats.pull_us.Observe(uint64_t(t1 - t0));
      entry.wire->pull_ops.Add(1);
      entry.wire->pull_rows.Add(cnt);
      entry.wire->bytes_out.Add(4 + uint64_t(flen));
      if (tid) {  // lifecycle spans: frame read -> gather+reply queued
        auto &tr = ptpu::trace::Global();
        tr.Record(tid, ptpu::trace::kRead, t_read, t0, conn->id(), cnt);
        tr.Record(tid, ptpu::trace::kPull, t0, t1, conn->id(), cnt);
      }
      if (ptpu::trace::Global().SlowEligible(t1 - t_read)) {
        const ptpu::trace::SpanRec sp[2] = {
            {ptpu::trace::kRead, t_read, t0},
            {ptpu::trace::kPull, t0, t1}};
        ptpu::trace::Global().RecordSlow(tid, conn->id(), cnt,
                                         t1 - t_read, sp, 2);
      }
      return FrameResult::kOk;
    }
    // [u8 flags][u32 n][u32 dim][ids][grads]
    if (n < off + 9) return proto_err();
    const bool is_async = req[off] != 0;
    (void)is_async;  // C applies inline — ack-after-apply is a
                     // strictly stronger contract than coalesce
    uint32_t cnt, d32;
    std::memcpy(&cnt, req + off + 1, 4);
    std::memcpy(&d32, req + off + 5, 4);
    off += 9;
    if (n != off + 8ull * cnt + 4ull * cnt * d32) return proto_err();
    const int64_t dim = ptpu_ps_table_dim(entry.table);
    const auto count_push = [&](uint32_t rows) {
      stats.push_ops.Add(1);
      stats.push_rows.Add(rows);
      stats.bytes_out.Add(6);  // 4B length + OK frame
      const int64_t t1 = ptpu::NowUs();
      stats.push_us.Observe(uint64_t(t1 - t0));
      entry.wire->push_ops.Add(1);
      entry.wire->push_rows.Add(rows);
      entry.wire->bytes_out.Add(6);
      if (tid) {
        auto &tr = ptpu::trace::Global();
        tr.Record(tid, ptpu::trace::kRead, t_read, t0, conn->id(),
                  rows);
        tr.Record(tid, ptpu::trace::kPush, t0, t1, conn->id(), rows);
      }
      if (ptpu::trace::Global().SlowEligible(t1 - t_read)) {
        const ptpu::trace::SpanRec sp[2] = {
            {ptpu::trace::kRead, t_read, t0},
            {ptpu::trace::kPush, t0, t1}};
        ptpu::trace::Global().RecordSlow(tid, conn->id(), rows,
                                         t1 - t_read, sp, 2);
      }
    };
    const auto send_ok = [&]() {
      const size_t ho = wire_tid ? size_t(ptpu::trace::kTraceExt) : 0;
      std::vector<uint8_t> rep = conn->AcquireBuf();
      rep.resize(6 + ho);
      rep[4] = wire_tid ? kWireVersionTraced : kWireVersion;
      rep[5] = kTagOk;
      if (wire_tid) ptpu::PutU64(rep.data() + 6, wire_tid);
      return conn->SendPayload(std::move(rep), tid, 0);
    };
    if (cnt == 0) {  // empty push (dim underivable): trivially ok
      if (!send_ok()) return FrameResult::kClose;
      count_push(0);
      return FrameResult::kOk;
    }
    if (int64_t(d32) != dim) {
      // application error, not a protocol error: the frame parsed
      // fine — answer like the Python plane instead of hanging up
      if (!SendErr(conn, "push dim " + std::to_string(d32) +
                             " != table dim " + std::to_string(dim)))
        return FrameResult::kClose;
      return FrameResult::kOk;
    }
    // ids/grads sit at arbitrary offsets (table-name length shifts
    // them): ids are read via the unaligned-safe GetI64; grads are
    // handed to the table as a BYTE pointer — ptpu_ps_table_push_raw
    // reads each f32 with memcpy, so no aligned copy is needed
    const uint8_t *ids_b = req + off;
    const uint8_t *grads_b = req + off + 8ull * cnt;
    // event-thread scratch, reused across frames (was per-conn)
    thread_local std::vector<int64_t> local;
    if (local.size() < cnt) local.resize(cnt);
    for (uint32_t i = 0; i < cnt; ++i)
      // unsigned wrap, not signed overflow — same hostile-id story as
      // the pull path above (corpus/wire_ps/crash-push-id-underflow.bin)
      local[i] = int64_t(uint64_t(ptpu::GetI64(ids_b + 8 * i)) -
                         uint64_t(entry.lo));
    if (ptpu_ps_table_push_raw(entry.table, local.data(), cnt,
                               grads_b) != 0) {
      if (!SendErr(conn, ptpu_ps_last_error()))
        return FrameResult::kClose;
      return FrameResult::kOk;
    }
    if (!send_ok()) return FrameResult::kClose;
    count_push(cnt);
    return FrameResult::kOk;
  }
};

std::string PsServer::StatsJson() {
  std::string out = "{\"server\":{";
  const ServerStats &st = stats;
  const ptpu::net::Stats &nt = net;
  const struct { const char *name; const ptpu::Counter *c; } cs[] = {
      {"pull_ops", &st.pull_ops},       {"pull_rows", &st.pull_rows},
      {"push_ops", &st.push_ops},       {"push_rows", &st.push_rows},
      {"bytes_in", &st.bytes_in},       {"bytes_out", &st.bytes_out},
      {"err_frames", &st.err_frames},   {"proto_errors", &st.proto_errors},
      {"cpu_us", &st.cpu_us},
      {"handshake_fails", &nt.handshake_fails},
      {"conns_accepted", &nt.conns_accepted},
      {"conns_closed", &nt.conns_closed},
      {"conns_shed", &nt.conns_shed},
      {"handshake_timeouts", &nt.handshake_timeouts},
      {"idle_closes", &nt.idle_closes},
      {"epoll_wakeups", &nt.epoll_wakeups},
      {"partial_write_flushes", &nt.partial_write_flushes},
      {"http_reqs", &nt.http_reqs},
      {"chaos_conn_kills", &nt.chaos_conn_kills},
      {"chaos_read_delays", &nt.chaos_read_delays},
      {"chaos_write_delays", &nt.chaos_write_delays},
      {"chaos_short_writes", &nt.chaos_short_writes},
      {"chaos_handshake_drops", &nt.chaos_handshake_drops},
  };
  for (const auto &kv : cs) {
    ptpu::AppendJsonU64(&out, kv.name, kv.c->Get());
    out += ',';
  }
  ptpu::AppendJsonU64(&out, "conns_active",
                      uint64_t(nt.active_conns.load(
                          std::memory_order_relaxed)));
  out += ',';
  ptpu::AppendJsonHist(&out, "pull_us", st.pull_us);
  out += ',';
  ptpu::AppendJsonHist(&out, "push_us", st.push_us);
  out += "},\"tables\":{";
  {
    ptpu::MutexLock g(mu);
    bool first = true;
    for (const auto &kv : tables) {
      if (!first) out += ',';
      first = false;
      out += '"';
      out += ptpu::JsonEscape(kv.first);
      out += "\":{\"wire\":{";
      const TableWireStats &w = *kv.second.wire;
      const struct { const char *name; const ptpu::Counter *c; } ws[] = {
          {"pull_ops", &w.pull_ops},   {"pull_rows", &w.pull_rows},
          {"push_ops", &w.push_ops},   {"push_rows", &w.push_rows},
          {"bytes_in", &w.bytes_in},   {"bytes_out", &w.bytes_out},
      };
      bool wfirst = true;
      for (const auto &c : ws) {
        if (!wfirst) out += ',';
        wfirst = false;
        ptpu::AppendJsonU64(&out, c.name, c.c->Get());
      }
      out += "},\"table\":";
      out += ptpu_ps_table_stats_json(kv.second.table);
      out += '}';
    }
  }
  out += "}}";
  return out;
}

thread_local std::string g_srv_error;

}  // namespace

PTPU_PS_EXPORT const char *ptpu_ps_server_last_error(void) {
  return g_srv_error.c_str();
}

PTPU_PS_EXPORT void *ptpu_ps_server_start2(int port,
                                           const char *authkey,
                                           int authkey_len,
                                           int loopback_only,
                                           int http_port);

// Start the data-plane server on `port` (0 picks a free one;
// ptpu_ps_server_port reports it). `loopback_only` nonzero binds
// 127.0.0.1 — single-host jobs must not expose pull/push to the
// network (the Python control plane makes the same choice). Returns
// NULL on error.
PTPU_PS_EXPORT void *ptpu_ps_server_start(int port, const char *authkey,
                                          int authkey_len,
                                          int loopback_only) {
  return ptpu_ps_server_start2(port, authkey, authkey_len,
                               loopback_only, -1);
}

// Extended start (ISSUE 10): http_port >= 0 adds the telemetry
// HTTP/1.1 listener (0 picks a free port; ptpu_ps_server_http_port
// reports it) served by the same epoll event threads. The
// PTPU_NET_HTTP env knob overrides either form.
PTPU_PS_EXPORT void *ptpu_ps_server_start2(int port,
                                           const char *authkey,
                                           int authkey_len,
                                           int loopback_only,
                                           int http_port) {
  auto *s = new PsServer();
  if (authkey && authkey_len > 0)
    s->authkey.assign(authkey, size_t(authkey_len));
  std::string err;
  if (!s->Start(port, loopback_only, http_port, &err)) {
    g_srv_error = "ptpu_ps_server_start: " + err;
    delete s;
    return nullptr;
  }
  return s;
}

// Handle-taking entries guard NULL like the table ABI: defined error
// returns beat segfaults when a binding races teardown.
PTPU_PS_EXPORT int ptpu_ps_server_port(void *h) {
  auto *s = static_cast<PsServer *>(h);
  return s ? s->port : -1;
}

// Expose `table` (a ptpu_ps_table handle) as `name` with global-id
// offset `lo` — the server subtracts lo before the bounds-checked
// local gather/scatter.
PTPU_PS_EXPORT int ptpu_ps_server_register(void *h, const char *name,
                                           void *table, int64_t lo) {
  auto *s = static_cast<PsServer *>(h);
  if (!s || !name || !table) {
    g_srv_error = "ptpu_ps_server_register: null handle or table";
    return -1;
  }
  ptpu::MutexLock g(s->mu);
  auto &ws = s->table_stats[name];
  if (!ws) ws.reset(new TableWireStats());
  s->tables[name] = ShardEntry{table, lo, ws.get()};
  return 0;
}

// JSON snapshot: {"server":{global wire counters + net-core conn
// counters + pull_us/push_us histograms}, "tables":{name:{"wire":
// {...},"table":{storage counters from ptpu_ps_table_stats_json}}}}.
// Returned pointer is a thread-local render buffer, valid until the
// calling thread's next ptpu_ps_server_stats_json call.
PTPU_PS_EXPORT const char *ptpu_ps_server_stats_json(void *h) {
  thread_local std::string g_json;
  auto *s = static_cast<PsServer *>(h);
  if (!s) return "{}";
  g_json = s->StatsJson();
  return g_json.c_str();
}

// Prometheus exposition text of the live stats snapshot — the same
// bytes GET /metrics serves (and byte-identical to profiler/stats.py
// prometheus_text over the stats_json snapshot). Thread-local buffer,
// valid until this thread's next call.
PTPU_PS_EXPORT const char *ptpu_ps_server_prom_text(void *h) {
  thread_local std::string g_prom;
  auto *s = static_cast<PsServer *>(h);
  if (!s) return "";
  g_prom = ptpu::trace::PromFromStatsJson(s->StatsJson(), "ptpu_ps");
  return g_prom.c_str();
}

// Telemetry HTTP port (GET /metrics /healthz /statsz /tracez), or -1
// when the endpoint is disabled.
PTPU_PS_EXPORT int ptpu_ps_server_http_port(void *h) {
  auto *s = static_cast<PsServer *>(h);
  if (!s || !s->net_srv) return -1;
  return s->net_srv->http_port();
}

// Reset wire counters (global + net-core + per-table) AND the storage
// counters of every registered table — one call zeroes the whole
// serving view.
PTPU_PS_EXPORT void ptpu_ps_server_stats_reset(void *h) {
  auto *s = static_cast<PsServer *>(h);
  if (!s) return;
  s->stats.Reset();
  s->net.Reset();
  ptpu::MutexLock g(s->mu);
  for (auto &kv : s->tables) {
    kv.second.wire->Reset();
    ptpu_ps_table_stats_reset(kv.second.table);
  }
}

PTPU_PS_EXPORT void ptpu_ps_server_stop(void *h) {
  auto *s = static_cast<PsServer *>(h);
  if (!s) return;
  s->Stop();
  delete s;
}
