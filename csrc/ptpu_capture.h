// ptpu_capture — sampled raw-frame capture rings + the persisted
// capture file format (ISSUE 18 tentpole a). The production-drill
// observability plane: a lock-free fixed-slot ring (the ptpu_trace
// seqlock pattern) records inbound FRAMED-wire frames as they are
// dispatched — timestamp, connection id, wire ver/tag bytes, full
// frame length, and a bounded payload prefix — so a live server can
// dump real traffic through GET /capturez (or ptpu_capture_save) for
// tools/drill_replay.py to re-fire against another instance.
//
// Shape:
//   * Sampling: PTPU_CAPTURE_SAMPLE = 0 (default) disables everything
//     — the zero-cost path is ONE relaxed load per frame; 1 captures
//     every frame, N captures 1-in-N. Runtime override via the
//     ptpu_capture_set ABI (csrc/ptpu_net.cc exports it into BOTH
//     shipping .so's).
//   * Ring: PTPU_CAPTURE_RING slots (pow2-rounded) with a
//     PTPU_CAPTURE_BYTES payload-prefix cap per slot. Writers publish
//     through the Boehm seqlock bracket (odd seq while writing, even
//     when done); readers drop torn slots — capture is observability,
//     not an audit log.
//   * File format: length-prefixed little-endian records through the
//     bounds-checked ptpu_wire.h codecs, with the r16 tune-cache
//     posture — UNTRUSTED DISK INPUT, exact-size-first validation,
//     whole-file reject on any malformed record, fuzzed end to end
//     (csrc/fuzz/fuzz_capture.cc). Capture files are per-machine
//     diagnostics, safe to delete.
//
// Everything is inline so the single-TU selftests and fuzz harnesses
// (#include "ptpu_net.cc" style) see one definition; the extern "C"
// ABI surface lives in ptpu_net.cc. Layout constants are mirrored by
// tools/drill_replay.py — the `wire` checker in tools/ptpu_check.py
// holds the two in lockstep.
#ifndef PTPU_CAPTURE_H_
#define PTPU_CAPTURE_H_

#include <stdio.h>
#include <unistd.h>

#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include "ptpu_schedck.h"
#include "ptpu_wire.h"

namespace ptpu {
namespace capture {

// ---------------------------------------------------------------------------
// capture file format "ptpu-capture v1"
// ---------------------------------------------------------------------------
//
//   [0]  u32  magic  "PCAP" (LE 0x50414350)
//   [4]  u32  version (1)
//   [8]  u32  count  (<= kCaptureMaxRecords)
//   [12] u32  body_bytes (byte length of everything after the header)
//   [16] count variable-length records:
//        [0]  i64 ts_us     (NowUs() steady clock of the capture)
//        [8]  u64 conn      (net-core connection id)
//        [16] u32 frame_len (full wire payload length)
//        [20] u32 cap_len   (prefix bytes stored; <= frame_len and
//                            <= kCaptureMaxRecPayload)
//        [24] u8  ver, u8 tag, u16 reserved (0)
//        [28] cap_len payload-prefix bytes
//
// The byte length must equal 16 + body_bytes EXACTLY, the record walk
// must consume exactly body_bytes yielding exactly count records, and
// ver/tag must equal the stored payload's first two bytes — any
// violation rejects the WHOLE file (never-crash/full-reject, the r16
// tune-cache rule). All fields little-endian via the unaligned-safe
// ptpu_wire.h codecs. Python twin: tools/drill_replay.py
// CAPTURE_MAGIC/CAPTURE_VERSION/CAPTURE_HEADER_BYTES/CAPTURE_REC_BYTES.

constexpr uint32_t kCaptureMagic = 0x50414350u;  // "PCAP"
constexpr uint32_t kCaptureVersion = 1;
constexpr uint32_t kCaptureMaxRecords = 65536;
constexpr size_t kCaptureHeaderBytes = 16;
constexpr size_t kCaptureRecBytes = 28;  // fixed part, before payload
constexpr size_t kCaptureMaxRecPayload = 4096;

enum class ParseResult {
  kOk = 0,     // well-formed, records returned
  kMalformed,  // corrupt bytes: adopt nothing
};

// One captured frame, as read back out of the ring or a file.
struct CapRecord {
  int64_t ts_us = 0;
  uint64_t conn = 0;
  uint32_t frame_len = 0;
  uint8_t ver = 0, tag = 0;
  std::vector<uint8_t> payload;  // cap_len prefix bytes
};

/* Bounds-checked parser over UNTRUSTED bytes. Never throws, never
 * reads past `size`, never adopts a file whose walk disagrees with
 * its own header. Fuzz target: csrc/fuzz/fuzz_capture.cc (corpus
 * csrc/fuzz/corpus/capture). */
inline ParseResult ParseCaptureBytes(const uint8_t* data, size_t size,
                                     std::vector<CapRecord>* out) {
  // *out is written ONLY on kOk (one swap at the end): a reject can
  // never leave a caller holding a half-adopted record list
  if (data == nullptr || size < kCaptureHeaderBytes)
    return ParseResult::kMalformed;
  if (GetU32(data) != kCaptureMagic) return ParseResult::kMalformed;
  if (GetU32(data + 4) != kCaptureVersion)
    return ParseResult::kMalformed;
  const uint32_t count = GetU32(data + 8);
  const uint32_t body_bytes = GetU32(data + 12);
  if (count > kCaptureMaxRecords) return ParseResult::kMalformed;
  // exact-size check BEFORE any record read: count/body_bytes are
  // attacker data, and the sum cannot overflow (both fit in u32)
  if (size != kCaptureHeaderBytes + size_t(body_bytes))
    return ParseResult::kMalformed;
  const uint8_t* body = data + kCaptureHeaderBytes;
  std::vector<CapRecord> parsed;
  parsed.reserve(count);
  size_t off = 0;
  for (uint32_t i = 0; i < count; ++i) {
    if (off + kCaptureRecBytes > size_t(body_bytes))
      return ParseResult::kMalformed;
    const uint8_t* r = body + off;
    CapRecord rec;
    rec.ts_us = GetI64(r);
    rec.conn = GetU64(r + 8);
    rec.frame_len = GetU32(r + 16);
    const uint32_t cap_len = GetU32(r + 20);
    rec.ver = r[24];
    rec.tag = r[25];
    if (GetU16(r + 26) != 0) return ParseResult::kMalformed;
    if (cap_len > rec.frame_len || cap_len > kCaptureMaxRecPayload)
      return ParseResult::kMalformed;
    if (off + kCaptureRecBytes + size_t(cap_len) > size_t(body_bytes))
      return ParseResult::kMalformed;
    const uint8_t* pl = r + kCaptureRecBytes;
    // ver/tag mirror the payload's leading bytes — a record whose
    // header disagrees with its own stored bytes was not written by
    // this code
    if ((cap_len >= 1 && rec.ver != pl[0]) ||
        (cap_len >= 2 && rec.tag != pl[1]) ||
        (cap_len < 1 && rec.ver != 0) || (cap_len < 2 && rec.tag != 0))
      return ParseResult::kMalformed;
    rec.payload.assign(pl, pl + cap_len);
    parsed.push_back(std::move(rec));
    off += kCaptureRecBytes + size_t(cap_len);
  }
  // no trailing garbage: the walk must land exactly on body_bytes
  if (off != size_t(body_bytes)) return ParseResult::kMalformed;
  out->swap(parsed);
  return ParseResult::kOk;
}

inline void SerializeCapture(const std::vector<CapRecord>& records,
                             std::vector<uint8_t>* out) {
  const size_t n = records.size() > kCaptureMaxRecords
                       ? kCaptureMaxRecords
                       : records.size();
  size_t body = 0;
  for (size_t i = 0; i < n; ++i) {
    const size_t cap = records[i].payload.size() > kCaptureMaxRecPayload
                           ? kCaptureMaxRecPayload
                           : records[i].payload.size();
    body += kCaptureRecBytes + cap;
  }
  out->assign(kCaptureHeaderBytes + body, 0);
  uint8_t* p = out->data();
  PutU32(p, kCaptureMagic);
  PutU32(p + 4, kCaptureVersion);
  PutU32(p + 8, uint32_t(n));
  PutU32(p + 12, uint32_t(body));
  size_t off = kCaptureHeaderBytes;
  for (size_t i = 0; i < n; ++i) {
    const CapRecord& rec = records[i];
    const size_t cap = rec.payload.size() > kCaptureMaxRecPayload
                           ? kCaptureMaxRecPayload
                           : rec.payload.size();
    uint8_t* r = p + off;
    PutI64(r, rec.ts_us);
    PutU64(r + 8, rec.conn);
    PutU32(r + 16, rec.frame_len);
    PutU32(r + 20, uint32_t(cap));
    r[24] = cap >= 1 ? rec.payload[0] : 0;
    r[25] = cap >= 2 ? rec.payload[1] : 0;
    PutU16(r + 26, 0);
    if (cap > 0) std::memcpy(r + kCaptureRecBytes, rec.payload.data(), cap);
    off += kCaptureRecBytes + cap;
  }
}

// ---------------------------------------------------------------------------
// knobs
// ---------------------------------------------------------------------------

struct Config {
  int64_t sample = 0;  // PTPU_CAPTURE_SAMPLE: 0 off (default), 1 all
  size_t ring = 1024;  // PTPU_CAPTURE_RING slots (pow2-rounded)
  size_t bytes = 256;  // PTPU_CAPTURE_BYTES payload-prefix cap
};

inline int64_t CaptureEnvI64(const char* name, int64_t dflt) {
  const char* e = std::getenv(name);
  if (!e || !*e) return dflt;
  char* end = nullptr;
  const long long v = std::strtoll(e, &end, 10);
  return (end && *end == '\0') ? int64_t(v) : dflt;
}

inline size_t CaptureRoundPow2(size_t v, size_t lo, size_t hi) {
  size_t p = lo;
  while (p < v && p < hi) p <<= 1;
  return p;
}

inline Config ConfigFromEnv() {
  Config cfg;
  cfg.sample = CaptureEnvI64("PTPU_CAPTURE_SAMPLE", cfg.sample);
  if (cfg.sample < 0) cfg.sample = 0;
  const int64_t ring =
      CaptureEnvI64("PTPU_CAPTURE_RING", int64_t(cfg.ring));
  if (ring > 0) cfg.ring = size_t(ring);
  const int64_t bytes =
      CaptureEnvI64("PTPU_CAPTURE_BYTES", int64_t(cfg.bytes));
  if (bytes > 0) cfg.bytes = size_t(bytes);
  if (cfg.bytes < 16) cfg.bytes = 16;
  if (cfg.bytes > kCaptureMaxRecPayload)
    cfg.bytes = kCaptureMaxRecPayload;
  return cfg;
}

// ---------------------------------------------------------------------------
// the ring
// ---------------------------------------------------------------------------

class Ring {
 public:
  explicit Ring(const Config& cfg)
      : sample_(cfg.sample),
        cap_bytes_(CaptureRoundPow2(cfg.bytes, 16, kCaptureMaxRecPayload)),
        ring_(CaptureRoundPow2(cfg.ring, 64, 1u << 20)),
        arena_(ring_.size() * cap_bytes_) {}

  // Sampling decision for one arriving frame. With sample == 0 this
  // is ONE relaxed load — the ≤3% capture-off overhead gate rides on
  // this path staying empty.
  bool Sampled() {
    const int64_t s = sample_.load(std::memory_order_relaxed);
    if (s <= 0) return false;
    if (s != 1 &&
        ctr_.fetch_add(1, std::memory_order_relaxed) % uint64_t(s) != 0)
      return false;
    return true;
  }

  /* Record one dispatched frame. Seqlock writer (Boehm, "Can seqlocks
   * get along with programming language memory models?" MSPC'12):
   * odd seq marks the slot mid-write, the release fence orders the
   * mark before every field store, and the final release store
   * publishes. Field + payload stores are relaxed atomics so a racing
   * reader's copies are not UB — torn values are discarded by the
   * reader's seq re-check. */
  void Record(int64_t ts_us, uint64_t conn, const uint8_t* payload,
              uint32_t n) {
    const uint64_t idx = head_.fetch_add(1, std::memory_order_relaxed);
    const size_t slot_i = idx & (ring_.size() - 1);
    Slot& s = ring_[slot_i];
    s.seq.store(2 * idx + 1, std::memory_order_relaxed);
    std::atomic_thread_fence(std::memory_order_release);
    PTPU_SCHED_POINT();
    s.ts_us.store(ts_us, std::memory_order_relaxed);
    s.conn.store(conn, std::memory_order_relaxed);
    s.frame_len.store(n, std::memory_order_relaxed);
    const uint32_t cap =
        n < uint32_t(cap_bytes_) ? n : uint32_t(cap_bytes_);
    s.cap_len.store(cap, std::memory_order_relaxed);
    s.ver.store(n >= 1 ? payload[0] : 0, std::memory_order_relaxed);
    s.tag.store(n >= 2 ? payload[1] : 0, std::memory_order_relaxed);
    std::atomic<uint8_t>* dst = arena_.data() + slot_i * cap_bytes_;
    for (uint32_t i = 0; i < cap; ++i)
      dst[i].store(payload[i], std::memory_order_relaxed);
    PTPU_SCHED_POINT();
    s.seq.store(2 * idx + 2, std::memory_order_release);
  }

  // Runtime override (ptpu_capture_set ABI): sample < 0 keeps the
  // current value. Ring/bytes stay env-only — they size allocations.
  void Set(int64_t sample) {
    if (sample >= 0) sample_.store(sample, std::memory_order_relaxed);
  }

  int64_t sample() const {
    return sample_.load(std::memory_order_relaxed);
  }
  uint64_t recorded() const {
    return head_.load(std::memory_order_relaxed);
  }
  size_t ring_size() const { return ring_.size(); }
  size_t cap_bytes() const { return cap_bytes_; }

  // Newest-first snapshot; torn slots (mid-overwrite) are skipped.
  void Snapshot(std::vector<CapRecord>* out, size_t max_n) const {
    out->clear();
    const uint64_t head = head_.load(std::memory_order_acquire);
    uint64_t n = head < ring_.size() ? head : ring_.size();
    if (n > max_n) n = max_n;
    for (uint64_t i = 0; i < n; ++i) {
      const uint64_t idx = head - 1 - i;
      const size_t slot_i = idx & (ring_.size() - 1);
      const Slot& s = ring_[slot_i];
      if (s.seq.load(std::memory_order_acquire) != 2 * idx + 2)
        continue;
      PTPU_SCHED_POINT();
      CapRecord rec;
      rec.ts_us = s.ts_us.load(std::memory_order_relaxed);
      rec.conn = s.conn.load(std::memory_order_relaxed);
      rec.frame_len = s.frame_len.load(std::memory_order_relaxed);
      uint32_t cap = s.cap_len.load(std::memory_order_relaxed);
      if (cap > cap_bytes_) cap = uint32_t(cap_bytes_);  // torn: bound
      rec.ver = s.ver.load(std::memory_order_relaxed);
      rec.tag = s.tag.load(std::memory_order_relaxed);
      rec.payload.resize(cap);
      const std::atomic<uint8_t>* src =
          arena_.data() + slot_i * cap_bytes_;
      for (uint32_t k = 0; k < cap; ++k)
        rec.payload[k] = src[k].load(std::memory_order_relaxed);
      std::atomic_thread_fence(std::memory_order_acquire);
      if (s.seq.load(std::memory_order_relaxed) != 2 * idx + 2)
        continue;  // overwritten mid-copy: drop the torn record
      out->push_back(std::move(rec));
    }
  }

  // {"sample","ring","bytes","recorded","frames":[...]} — the GET
  // /capturez body. Payload prefixes are lowercase hex.
  std::string CapturezJson(size_t max_n) const {
    std::vector<CapRecord> recs;
    Snapshot(&recs, max_n);
    std::string out = "{\"sample\":";
    out += std::to_string(sample());
    out += ",\"ring\":";
    out += std::to_string(ring_.size());
    out += ",\"bytes\":";
    out += std::to_string(cap_bytes_);
    out += ",\"recorded\":";
    out += std::to_string(recorded());
    out += ",\"frames\":[";
    static const char* hex = "0123456789abcdef";
    for (size_t i = 0; i < recs.size(); ++i) {
      const CapRecord& r = recs[i];
      if (i) out += ',';
      out += "{\"ts_us\":";
      out += std::to_string(r.ts_us);
      out += ",\"conn\":";
      out += std::to_string(r.conn);
      out += ",\"len\":";
      out += std::to_string(r.frame_len);
      out += ",\"ver\":";
      out += std::to_string(unsigned(r.ver));
      out += ",\"tag\":";
      out += std::to_string(unsigned(r.tag));
      out += ",\"data\":\"";
      for (uint8_t b : r.payload) {
        out += hex[b >> 4];
        out += hex[b & 0xf];
      }
      out += "\"}";
    }
    out += "]}";
    return out;
  }

  /* Dump the ring (oldest-first, every readable slot) into a capture
   * file via tmp + rename (the tune-cache save idiom — a concurrent
   * reader never sees a torn file). Returns records written, -1 on
   * I/O error. */
  int SaveFile(const std::string& path) const {
    std::vector<CapRecord> recs;
    Snapshot(&recs, kCaptureMaxRecords);
    // Snapshot is newest-first; a replay wants arrival order
    std::vector<CapRecord> ordered(recs.rbegin(), recs.rend());
    std::vector<uint8_t> bytes;
    SerializeCapture(ordered, &bytes);
    const std::string tmp = path + ".tmp." + std::to_string(::getpid());
    FILE* f = std::fopen(tmp.c_str(), "wb");
    bool ok = f != nullptr;
    if (ok) {
      ok = std::fwrite(bytes.data(), 1, bytes.size(), f) == bytes.size();
      ok = (std::fclose(f) == 0) && ok;
    }
    if (ok) ok = ::rename(tmp.c_str(), path.c_str()) == 0;
    if (!ok) {
      ::unlink(tmp.c_str());
      return -1;
    }
    return int(ordered.size());
  }

 private:
  struct Slot {
    std::atomic<uint64_t> seq{0};  // 2*idx+1 writing, 2*idx+2 done
    std::atomic<int64_t> ts_us{0};
    std::atomic<uint64_t> conn{0};
    std::atomic<uint32_t> frame_len{0}, cap_len{0};
    std::atomic<uint8_t> ver{0}, tag{0};
  };

  std::atomic<int64_t> sample_;
  std::atomic<uint64_t> head_{0}, ctr_{0};
  const size_t cap_bytes_;
  std::vector<Slot> ring_;  // size is a power of two
  // payload-prefix arena: slot i owns bytes [i*cap_bytes_, (i+1)*..);
  // relaxed byte stores inside the seqlock bracket keep racing
  // readers defined (torn copies are dropped by the seq re-check)
  std::vector<std::atomic<uint8_t>> arena_;
};

// Process-global ring for this shared object, lazily constructed from
// the PTPU_CAPTURE_* env on first touch. Heap-allocated and never
// destroyed (immortal): event threads may record during static
// destruction of the host, and LSan treats reachable globals as live.
inline Ring& Global() {
  static Ring* g = new Ring(ConfigFromEnv());
  return *g;
}

}  // namespace capture
}  // namespace ptpu

#endif  // PTPU_CAPTURE_H_
