// Shared TCP wire machinery for the native servers — exact-length
// socket I/O, u32-LE frame length codec, and the HMAC-SHA256 nonce
// handshake — used by BOTH the PS data plane (csrc/ptpu_ps_server.cc)
// and the inference serving runtime (csrc/ptpu_serving.cc). Factored
// so a fix lands once (the two serve loops themselves differ: table
// gather/scatter vs batcher enqueue).
#ifndef PTPU_WIRE_H_
#define PTPU_WIRE_H_

#include <errno.h>
#include <unistd.h>

#include <cstdint>
#include <cstring>
#include <random>
#include <string>

#include "ptpu_hmac.h"

namespace ptpu {

inline bool ReadExact(int fd, void *p, size_t n) {
  auto *c = static_cast<char *>(p);
  while (n) {
    const ssize_t r = ::read(fd, c, n);
    if (r <= 0) return false;
    c += r;
    n -= size_t(r);
  }
  return true;
}

inline bool WriteExact(int fd, const void *p, size_t n) {
  auto *c = static_cast<const char *>(p);
  while (n) {
    const ssize_t r = ::write(fd, c, n);
    if (r <= 0) return false;
    c += r;
    n -= size_t(r);
  }
  return true;
}

/* Unaligned-safe little-endian field codec. Wire frames pack fields at
 * arbitrary byte offsets (a table-name or dim count shifts everything
 * after it), so a cast-deref like *(const uint32_t*)p is undefined
 * behavior the moment the offset is not a multiple of the type's
 * alignment — UBSan's -fsanitize=alignment flags it on real frames.
 * Every multi-byte field therefore goes through these helpers: the
 * byte-wise forms are explicit LE, the memcpy forms compile to a
 * single unaligned mov on x86/arm64 (no cost) and are well-defined on
 * any alignment. Use these — never cast-deref into a frame buffer. */
inline void PutU32(uint8_t *p, uint32_t v) {
  p[0] = uint8_t(v);
  p[1] = uint8_t(v >> 8);
  p[2] = uint8_t(v >> 16);
  p[3] = uint8_t(v >> 24);
}

inline uint32_t GetU32(const uint8_t *p) {
  return uint32_t(p[0]) | uint32_t(p[1]) << 8 | uint32_t(p[2]) << 16 |
         uint32_t(p[3]) << 24;
}

inline void PutU64(uint8_t *p, uint64_t v) {
  PutU32(p, uint32_t(v));
  PutU32(p + 4, uint32_t(v >> 32));
}

inline uint64_t GetU64(const uint8_t *p) {
  return uint64_t(GetU32(p)) | uint64_t(GetU32(p + 4)) << 32;
}

inline void PutU16(uint8_t *p, uint16_t v) {
  p[0] = uint8_t(v);
  p[1] = uint8_t(v >> 8);
}

inline uint16_t GetU16(const uint8_t *p) {
  return uint16_t(uint16_t(p[0]) | uint16_t(p[1]) << 8);
}

inline void PutI64(uint8_t *p, int64_t v) { PutU64(p, uint64_t(v)); }

inline int64_t GetI64(const uint8_t *p) { return int64_t(GetU64(p)); }

/* f32/f64 fields are IEEE-754 bit patterns in LE byte order (numpy
 * '<f4'/'<f8'); memcpy through the same-width integer keeps the value
 * bit-exact without ever forming a misaligned float reference. */
inline void PutF32(uint8_t *p, float v) {
  uint32_t bits;
  std::memcpy(&bits, &v, 4);
  PutU32(p, bits);
}

inline float GetF32(const uint8_t *p) {
  const uint32_t bits = GetU32(p);
  float v;
  std::memcpy(&v, &bits, 4);
  return v;
}

/* Server side of the connect handshake: send a 16-byte random nonce,
 * expect one frame holding HMAC-SHA256(authkey, nonce), answer one
 * byte 0x01 (the multiprocessing.connection HMAC challenge restated
 * for C peers). Constant-time MAC compare. */
inline bool ServerHandshake(int fd, const std::string &authkey) {
  uint8_t nonce[16];
  std::random_device rd;
  for (auto &b : nonce) b = uint8_t(rd());
  if (!WriteExact(fd, nonce, sizeof(nonce))) return false;
  uint8_t lenb[4];
  if (!ReadExact(fd, lenb, 4)) return false;
  if (GetU32(lenb) != 32) return false;
  uint8_t got[32], want[32];
  if (!ReadExact(fd, got, 32)) return false;
  HmacSha256(reinterpret_cast<const uint8_t *>(authkey.data()),
             authkey.size(), nonce, sizeof(nonce), want);
  uint8_t diff = 0;
  for (int i = 0; i < 32; ++i) diff |= uint8_t(got[i] ^ want[i]);
  if (diff) return false;
  const uint8_t ok = 0x01;
  return WriteExact(fd, &ok, 1);
}

/* accept() errno triage for the server loops: a transient failure
 * (peer RST before accept, EINTR, momentary fd exhaustion) must not
 * permanently stop a serving process from accepting — only a closed
 * listener (Stop) ends the loop. */
inline bool AcceptErrnoIsTransient(int err) {
  return err == ECONNABORTED || err == EINTR || err == EMFILE ||
         err == ENFILE || err == ENOBUFS || err == ENOMEM ||
         err == EPROTO;
}

}  // namespace ptpu

#endif  // PTPU_WIRE_H_
