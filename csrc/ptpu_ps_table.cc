// Native parameter-server shard table — the C-hosted PS hot path.
//
// Reference counterpart: distributed/ps/table/memory_sparse_table.cc +
// common_dense_table.cc behind the brpc service
// (distributed/service/brpc_ps_server.cc): row storage lives in the
// server process, the optimizer runs inside the table on push, and the
// wire only ever moves contiguous row blocks. The Python table service
// (paddle_tpu/distributed/ps/table.py) keeps protocol/routing and
// delegates the per-row work here via ctypes.
//
// Layout: one contiguous allocation per shard whose internal offsets
// (weights, optimizer slots, per-row step counters) are planned by the
// shared ptpu::PlanArena (csrc/ptpu_arena.h) — the same best-fit
// machinery the runtime allocator and the predictor's memory planner
// use. Concurrency: pulls take a shared lock and run in parallel
// (the table service serves each accepted connection from its own
// thread); pushes take the exclusive lock.

#include "ptpu_ps_table.h"

#include <cmath>
#include <cstdlib>
#include <cstring>
#include <new>
#include <string>
#include <vector>

#include "ptpu_arena.h"
#include "ptpu_stats.h"
#include "ptpu_sync.h"

namespace {

// Per-table storage lock (rank table: README "Correctness tooling"):
// the LEAF of the PS plane — shared for pulls, exclusive for pushes,
// held only around the row copy / optimizer update, never across a
// send or another lock.
PTPU_LOCK_CLASS(kLockPsTable, "ps.table", 50);

thread_local std::string g_last_error;

void set_error(const std::string &msg) { g_last_error = msg; }

struct PsTable {
  int64_t rows = 0;
  int64_t dim = 0;
  int optimizer = PTPU_PS_SGD;
  float lr = 0.1f;
  float beta1 = 0.9f;
  float beta2 = 0.999f;
  float eps = 1e-8f;

  // one arena block; offsets planned by PlanArena
  char *base = nullptr;
  uint64_t bytes = 0;
  float *w = nullptr;        // rows * dim weights
  float *slot0 = nullptr;    // adagrad g2 / adam m   (rows * dim)
  float *slot1 = nullptr;    // adam v                (rows * dim)
  int64_t *steps = nullptr;  // adam per-row step count (rows)

  ptpu::SharedMutex mu{kLockPsTable};

  // storage-level counters (ptpu_stats.h): relaxed atomics, safe to
  // bump under either lock mode and to snapshot without any lock
  ptpu::Counter pull_ops, pull_rows, push_ops, push_rows,
      push_coalesced_rows;

  // push scratch, reused across calls (guarded by the exclusive lock):
  // open-addressed id->slot map + first-seen unique list + accumulators
  std::vector<int64_t> hash_keys;
  std::vector<int32_t> hash_slots;
  std::vector<int64_t> uniq;
  std::vector<float> acc;
};

// Coalesce duplicate ids: fills t->uniq (first-seen order) and t->acc
// (per-unique accumulated grads, accumulation following the original
// occurrence order — the same order np.add.at applies). Returns false
// on an out-of-range id. `grads` is a BYTE pointer: the data-plane
// server hands a view into the received frame, whose f32 block lands
// at whatever offset the table-name length left it — each value is
// read with a 4-byte memcpy (one unaligned mov, no copy, no UB).
bool coalesce(PsTable *t, const int64_t *ids, int64_t n,
              const unsigned char *grads) {
  const int64_t dim = t->dim;
  uint64_t cap = 16;
  while (cap < uint64_t(n) * 2) cap <<= 1;
  t->hash_keys.assign(cap, -1);
  t->hash_slots.assign(cap, -1);
  t->uniq.clear();
  t->acc.clear();
  const uint64_t mask = cap - 1;
  for (int64_t i = 0; i < n; ++i) {
    const int64_t id = ids[i];
    if (id < 0 || id >= t->rows) {
      set_error("ptpu_ps_table_push: id " + std::to_string(id) +
                " out of range [0, " + std::to_string(t->rows) + ")");
      return false;
    }
    // splitmix-style scramble keeps clustered id ranges from probing
    uint64_t hpos = (uint64_t(id) * 0x9E3779B97F4A7C15ull) & mask;
    int32_t slot = -1;
    for (;;) {
      const int64_t k = t->hash_keys[hpos];
      if (k == id) {
        slot = t->hash_slots[hpos];
        break;
      }
      if (k < 0) {
        slot = int32_t(t->uniq.size());
        t->hash_keys[hpos] = id;
        t->hash_slots[hpos] = slot;
        t->uniq.push_back(id);
        t->acc.resize(t->acc.size() + dim, 0.f);
        break;
      }
      hpos = (hpos + 1) & mask;
    }
    float *a = t->acc.data() + int64_t(slot) * dim;
    const unsigned char *g = grads + size_t(i) * size_t(dim) * 4;
    for (int64_t d = 0; d < dim; ++d) {
      float gv;
      std::memcpy(&gv, g + 4 * d, 4);
      a[d] += gv;
    }
  }
  return true;
}

void apply_update(PsTable *t) {
  const int64_t dim = t->dim;
  const float lr = t->lr;
  for (size_t u = 0; u < t->uniq.size(); ++u) {
    const int64_t row = t->uniq[u];
    const float *g = t->acc.data() + int64_t(u) * dim;
    float *w = t->w + row * dim;
    switch (t->optimizer) {
      case PTPU_PS_SGD:
        for (int64_t d = 0; d < dim; ++d) w[d] -= lr * g[d];
        break;
      case PTPU_PS_ADAGRAD: {
        float *g2 = t->slot0 + row * dim;
        for (int64_t d = 0; d < dim; ++d) {
          g2[d] += g[d] * g[d];
          w[d] -= lr * g[d] / (std::sqrt(g2[d]) + t->eps);
        }
        break;
      }
      case PTPU_PS_ADAM: {
        // per-row step count — the sparse-Adam contract: a row's bias
        // correction advances only when the row is touched (reference:
        // table/sparse_sgd_rule.cc SparseAdamSGDRule)
        float *m = t->slot0 + row * dim;
        float *v = t->slot1 + row * dim;
        const int64_t step = ++t->steps[row];
        const float bc1 = 1.f - std::pow(t->beta1, float(step));
        const float bc2 = 1.f - std::pow(t->beta2, float(step));
        for (int64_t d = 0; d < dim; ++d) {
          m[d] = t->beta1 * m[d] + (1.f - t->beta1) * g[d];
          v[d] = t->beta2 * v[d] + (1.f - t->beta2) * g[d] * g[d];
          const float mhat = m[d] / bc1;
          const float vhat = v[d] / bc2;
          w[d] -= lr * mhat / (std::sqrt(vhat) + t->eps);
        }
        break;
      }
    }
  }
}

}  // namespace

PTPU_PS_EXPORT const char *ptpu_ps_last_error(void) {
  return g_last_error.c_str();
}

PTPU_PS_EXPORT const char *ptpu_ps_version(void) { return "ptpu-ps-1"; }

PTPU_PS_EXPORT void *ptpu_ps_table_create(int64_t rows, int64_t dim,
                                          int optimizer, float lr,
                                          float beta1, float beta2,
                                          float eps) {
  if (rows <= 0 || dim <= 0) {
    set_error("ptpu_ps_table_create: rows and dim must be positive");
    return nullptr;
  }
  if (optimizer < PTPU_PS_SGD || optimizer > PTPU_PS_ADAM) {
    set_error("ptpu_ps_table_create: unknown optimizer kind " +
              std::to_string(optimizer));
    return nullptr;
  }
  auto *t = new (std::nothrow) PsTable();
  if (!t) {
    set_error("ptpu_ps_table_create: out of memory");
    return nullptr;
  }
  t->rows = rows;
  t->dim = dim;
  t->optimizer = optimizer;
  t->lr = lr;
  t->beta1 = beta1;
  t->beta2 = beta2;
  t->eps = eps;

  // plan the single block: weights + whatever slots the optimizer
  // needs, 64B-aligned offsets from the shared planner
  ptpu::PlanArena plan(64);
  const size_t wn = size_t(rows) * size_t(dim) * sizeof(float);
  const uint64_t off_w = plan.Alloc(wn);
  uint64_t off_s0 = 0, off_s1 = 0, off_steps = 0;
  const bool has_s0 = optimizer != PTPU_PS_SGD;
  const bool has_s1 = optimizer == PTPU_PS_ADAM;
  if (has_s0) off_s0 = plan.Alloc(wn);
  if (has_s1) {
    off_s1 = plan.Alloc(wn);
    off_steps = plan.Alloc(size_t(rows) * sizeof(int64_t));
  }
  t->bytes = plan.Size();
  t->base = static_cast<char *>(std::calloc(1, t->bytes));
  if (!t->base) {
    set_error("ptpu_ps_table_create: allocation of " +
              std::to_string(t->bytes) + " bytes failed");
    delete t;
    return nullptr;
  }
  t->w = reinterpret_cast<float *>(t->base + off_w);
  if (has_s0) t->slot0 = reinterpret_cast<float *>(t->base + off_s0);
  if (has_s1) {
    t->slot1 = reinterpret_cast<float *>(t->base + off_s1);
    t->steps = reinterpret_cast<int64_t *>(t->base + off_steps);
  }
  return t;
}

PTPU_PS_EXPORT void ptpu_ps_table_destroy(void *h) {
  auto *t = static_cast<PsTable *>(h);
  if (!t) return;
  std::free(t->base);
  delete t;
}

// Every handle-taking entry guards against a NULL handle: the ABI is
// consumed from ctypes/cgo where a teardown race or a failed create
// can hand back a null — a defined error return beats a segfault.
PTPU_PS_EXPORT float *ptpu_ps_table_data(void *h) {
  auto *t = static_cast<PsTable *>(h);
  return t ? t->w : nullptr;
}

PTPU_PS_EXPORT int64_t ptpu_ps_table_rows(void *h) {
  auto *t = static_cast<PsTable *>(h);
  return t ? t->rows : 0;
}

PTPU_PS_EXPORT int64_t ptpu_ps_table_dim(void *h) {
  auto *t = static_cast<PsTable *>(h);
  return t ? t->dim : 0;
}

PTPU_PS_EXPORT uint64_t ptpu_ps_table_bytes(void *h) {
  auto *t = static_cast<PsTable *>(h);
  return t ? t->bytes : 0;
}

PTPU_PS_EXPORT int ptpu_ps_table_pull(void *h, const int64_t *ids,
                                      int64_t n, float *out) {
  auto *t = static_cast<PsTable *>(h);
  if (!t || !ids || !out) {
    set_error("ptpu_ps_table_pull: null handle or buffer");
    return -1;
  }
  const int64_t dim = t->dim;
  ptpu::SharedLock lock(t->mu);
  for (int64_t i = 0; i < n; ++i) {
    const int64_t id = ids[i];
    if (id < 0 || id >= t->rows) {
      set_error("ptpu_ps_table_pull: id " + std::to_string(id) +
                " out of range [0, " + std::to_string(t->rows) + ")");
      return -1;
    }
    std::memcpy(out + i * dim, t->w + id * dim, size_t(dim) * sizeof(float));
  }
  t->pull_ops.Add(1);
  t->pull_rows.Add(uint64_t(n));
  return 0;
}

PTPU_PS_EXPORT int ptpu_ps_table_push_raw(void *h, const int64_t *ids,
                                          int64_t n,
                                          const void *grads) {
  auto *t = static_cast<PsTable *>(h);
  if (!t || !ids || !grads) {
    set_error("ptpu_ps_table_push: null handle or buffer");
    return -1;
  }
  if (n <= 0) return 0;
  ptpu::SharedUniqueLock lock(t->mu);
  if (!coalesce(t, ids, n, static_cast<const unsigned char *>(grads)))
    return -1;
  apply_update(t);
  t->push_ops.Add(1);
  t->push_rows.Add(uint64_t(n));
  t->push_coalesced_rows.Add(uint64_t(n) - t->uniq.size());
  return 0;
}

PTPU_PS_EXPORT int ptpu_ps_table_push(void *h, const int64_t *ids,
                                      int64_t n, const float *grads) {
  return ptpu_ps_table_push_raw(h, ids, n, grads);
}

PTPU_PS_EXPORT void ptpu_ps_table_rdlock(void *h) {
  auto *t = static_cast<PsTable *>(h);
  if (!t) return;
  t->mu.lock_shared();
}

PTPU_PS_EXPORT void ptpu_ps_table_rdunlock(void *h) {
  auto *t = static_cast<PsTable *>(h);
  if (!t) return;
  t->mu.unlock_shared();
}

PTPU_PS_EXPORT void ptpu_ps_table_note_pull(void *h, int64_t nrows) {
  auto *t = static_cast<PsTable *>(h);
  if (!t) return;
  t->pull_ops.Add(1);
  t->pull_rows.Add(uint64_t(nrows));
}

PTPU_PS_EXPORT const char *ptpu_ps_table_stats_json(void *h) {
  // thread_local render buffer (like g_last_error): concurrent
  // snapshotters never clobber each other's in-flight c_str
  thread_local std::string g_stats_json;
  auto *t = static_cast<PsTable *>(h);
  if (!t) return "{}";
  std::string out = "{";
  ptpu::AppendJsonU64(&out, "pull_ops", t->pull_ops.Get());
  out += ',';
  ptpu::AppendJsonU64(&out, "pull_rows", t->pull_rows.Get());
  out += ',';
  ptpu::AppendJsonU64(&out, "push_ops", t->push_ops.Get());
  out += ',';
  ptpu::AppendJsonU64(&out, "push_rows", t->push_rows.Get());
  out += ',';
  ptpu::AppendJsonU64(&out, "push_coalesced_rows",
                      t->push_coalesced_rows.Get());
  out += '}';
  g_stats_json.swap(out);
  return g_stats_json.c_str();
}

PTPU_PS_EXPORT void ptpu_ps_table_stats_reset(void *h) {
  auto *t = static_cast<PsTable *>(h);
  if (!t) return;
  t->pull_ops.Reset();
  t->pull_rows.Reset();
  t->push_ops.Reset();
  t->push_rows.Reset();
  t->push_coalesced_rows.Reset();
}
