// Shared lock-free stats core for the native subsystems (reference:
// platform/monitor.h StatValue + the bvar counters behind brpc's
// /vars page). One header, no TU: relaxed-atomic counters and
// fixed-bucket log2 latency histograms that both the native predictor
// (csrc/ptpu_predictor.cc) and the PS table/server
// (csrc/ptpu_ps_table.cc, csrc/ptpu_ps_server.cc) embed, plus the
// JSON render helpers their *_stats_json ABI calls share.
//
// Cost model: always-on. An idle subsystem pays nothing; a hot path
// pays one relaxed fetch_add per counter touch and three per
// histogram observation — no locks, no allocation, no syscalls.
// Python keeps the SAME bucket layout (paddle_tpu/profiler/stats.py)
// so native and fallback snapshots merge bucket-for-bucket.
#ifndef PTPU_STATS_H_
#define PTPU_STATS_H_

#include <time.h>

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <string>

namespace ptpu {

inline int64_t NowUs() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

/* Calling thread's consumed CPU time in microseconds
 * (CLOCK_THREAD_CPUTIME_ID). Hot paths take deltas around a request's
 * CPU-owning section and aggregate them into a plane's `cpu_us`
 * counter, so /statsz and the benches report cycles-per-request
 * directly — on a loopback-bandwidth-capped box, CPU/request is the
 * perf metric wall time cannot see (ISSUE 17). */
inline int64_t ThreadCpuUs() {
  struct timespec ts;
  if (clock_gettime(CLOCK_THREAD_CPUTIME_ID, &ts) != 0) return 0;
  return int64_t(ts.tv_sec) * 1000000 + int64_t(ts.tv_nsec) / 1000;
}

struct Counter {
  std::atomic<uint64_t> v{0};

  void Add(uint64_t d) { v.fetch_add(d, std::memory_order_relaxed); }
  uint64_t Get() const { return v.load(std::memory_order_relaxed); }
  void Reset() { v.store(0, std::memory_order_relaxed); }
  // Subtract a previously-read base without losing racing bumps —
  // the invariant-preserving stats_reset primitive (ISSUE 20):
  // zeroing a flow counter mid-flight breaks conservation laws
  // (requests == replies + errors), but subtracting a base that
  // itself satisfies the law preserves it by construction, racing
  // traffic included (the skew cancels algebraically). Unsigned
  // wraparound is the correct arithmetic here: base was read from
  // this counter, so the running sum stays non-negative.
  void Rebase(uint64_t base) {
    v.fetch_sub(base, std::memory_order_relaxed);
  }
};

// Log2 histogram: bucket 0 counts value 0, bucket b (1..kHistBuckets-2)
// counts values in [2^(b-1), 2^b), the last bucket is the overflow
// tail. 32 buckets cover 0 .. >1073s when values are microseconds.
constexpr int kHistBuckets = 32;

inline int HistBucketOf(uint64_t v) {
  if (v == 0) return 0;
  int bits = 0;
#if defined(__GNUC__) || defined(__clang__)
  bits = 64 - __builtin_clzll(v);
#else
  while (v) {
    ++bits;
    v >>= 1;
  }
#endif
  return bits < kHistBuckets - 1 ? bits : kHistBuckets - 1;
}

struct Histogram {
  std::atomic<uint64_t> buckets[kHistBuckets] = {};
  std::atomic<uint64_t> count{0};
  std::atomic<uint64_t> sum{0};

  void Observe(uint64_t v) {
    buckets[HistBucketOf(v)].fetch_add(1, std::memory_order_relaxed);
    count.fetch_add(1, std::memory_order_relaxed);
    sum.fetch_add(v, std::memory_order_relaxed);
  }

  void Reset() {
    for (auto &b : buckets) b.store(0, std::memory_order_relaxed);
    count.store(0, std::memory_order_relaxed);
    sum.store(0, std::memory_order_relaxed);
  }
};

inline std::string JsonEscape(const std::string &s) {
  std::string out;
  out.reserve(s.size() + 8);
  for (unsigned char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\r': out += "\\r"; break;
      case '\t': out += "\\t"; break;
      default:
        if (c < 0x20) {
          char buf[8];
          std::snprintf(buf, sizeof(buf), "\\u%04x", c);
          out += buf;
        } else {
          out += static_cast<char>(c);
        }
    }
  }
  return out;
}

// `"name":value` — callers add the separating commas/braces.
inline void AppendJsonU64(std::string *out, const char *name,
                          uint64_t v) {
  *out += '"';
  *out += name;
  *out += "\":";
  *out += std::to_string(v);
}

// `"name":{"count":..,"sum":..,"buckets":[..]}` — the shape
// paddle_tpu/profiler/stats.py Histogram.to_dict() emits, so snapshots
// from either side merge field-for-field.
inline void AppendJsonHist(std::string *out, const char *name,
                           const Histogram &h) {
  *out += '"';
  *out += name;
  *out += "\":{";
  AppendJsonU64(out, "count", h.count.load(std::memory_order_relaxed));
  *out += ',';
  AppendJsonU64(out, "sum", h.sum.load(std::memory_order_relaxed));
  *out += ",\"buckets\":[";
  for (int b = 0; b < kHistBuckets; ++b) {
    if (b) *out += ',';
    *out += std::to_string(
        h.buckets[b].load(std::memory_order_relaxed));
  }
  *out += "]}";
}

}  // namespace ptpu

#endif  // PTPU_STATS_H_
