// paddle_tpu native runtime core.
//
// TPU-native equivalents of the reference's C++ runtime subsystems
// (reference paths relative to /root/reference/paddle/fluid):
//   * BestFitArena        — memory/allocation/auto_growth_best_fit_allocator.cc
//                           (host staging buffers; device memory is XLA's)
//   * BlockingQueue       — framework/blocking_queue.h +
//                           operators/reader/lod_tensor_blocking_queue.h
//                           (DataLoader prefetch pipeline synchronization)
//   * Profiler            — platform/profiler.{h,cc} RecordEvent +
//                           chrome-trace export (tools/timeline.py)
//   * Monitor             — platform/monitor.h StatValue registry
//   * AES-CTR cipher      — framework/io/crypto/aes_cipher.cc
//                           (encrypted checkpoint save/load)
//
// Exposed as a flat C ABI consumed via ctypes (paddle_tpu/core/native.py).
// The compute path is XLA; this library is the runtime *around* it.

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <map>
#include <string>
#include <thread>
#include <vector>

#include "ptpu_arena.h"
#include "ptpu_sync.h"

#if defined(_WIN32)
#define PTPU_EXPORT extern "C" __declspec(dllexport)
#else
#define PTPU_EXPORT extern "C" __attribute__((visibility("default")))
#endif

// ---------------------------------------------------------------------------
// Error reporting (reference: platform/enforce.h PADDLE_ENFORCE_* — the rich
// error string travels to Python instead of aborting).
// ---------------------------------------------------------------------------
static thread_local std::string g_last_error;

static void set_error(const std::string &msg) { g_last_error = msg; }

PTPU_EXPORT const char *ptpu_last_error() { return g_last_error.c_str(); }

// ---------------------------------------------------------------------------
// BestFitArena — growing best-fit host allocator.
//
// Mirrors AutoGrowthBestFitAllocator: allocation rounded to an alignment
// unit, free blocks kept in a size-ordered multimap, adjacent free blocks
// coalesced, arena grows by max(chunk, request) when no block fits. The
// free-block bookkeeping is the shared ptpu::BestFitFreeList
// (csrc/ptpu_arena.h), the same machinery the native predictor's static
// memory planner uses in offset space.
// ---------------------------------------------------------------------------
// Lock classes of the runtime .so (rank table: README "Correctness
// tooling"): none of these ever nest with another — each is a leaf
// guarding one structure, ranked distinctly so any future nesting has
// a defined order.
PTPU_LOCK_CLASS(kLockRtArena, "rt.arena", 80);
PTPU_LOCK_CLASS(kLockRtQueue, "rt.queue", 82);
PTPU_LOCK_CLASS(kLockRtProfiler, "rt.profiler", 84);
PTPU_LOCK_CLASS(kLockRtStats, "rt.stats", 86);

namespace {

struct Chunk {
  void *base;
  size_t size;
};

class BestFitArena {
 public:
  explicit BestFitArena(size_t chunk_size, size_t alignment)
      : chunk_size_(chunk_size), align_(alignment) {}

  ~BestFitArena() {
    for (auto &c : chunks_) std::free(c.base);
  }

  void *Alloc(size_t n) {
    ptpu::MutexLock g(mu_);
    // zero-size requests round up to one alignment unit: n==0 would erase
    // a free block yet re-add the whole block at the same base, leaving
    // the address simultaneously free and allocated
    if (n == 0) n = 1;
    n = RoundUp(n);
    char *base;
    size_t block;
    if (!free_.Take(n, &base, &block)) {
      if (!Grow(n)) return nullptr;
      if (!free_.Take(n, &base, &block)) return nullptr;
    }
    if (block > n) free_.Add(base + n, block - n);
    allocated_[base] = n;
    in_use_ += n;
    peak_ = std::max(peak_, in_use_);
    return base;
  }

  bool Free(void *p) {
    ptpu::MutexLock g(mu_);
    auto it = allocated_.find(p);
    if (it == allocated_.end()) return false;
    size_t n = it->second;
    allocated_.erase(it);
    in_use_ -= n;
    free_.Add(static_cast<char *>(p), n);
    return true;
  }

  size_t InUse() const { return in_use_; }
  size_t Peak() const { return peak_; }
  size_t Reserved() const { return reserved_; }

 private:
  size_t RoundUp(size_t n) const { return (n + align_ - 1) / align_ * align_; }

  bool Grow(size_t need) {
    size_t sz = std::max(chunk_size_, need);
    void *base = nullptr;
#if defined(_WIN32)
    base = _aligned_malloc(sz, align_);
#else
    if (posix_memalign(&base, std::max<size_t>(align_, 64), sz) != 0)
      base = nullptr;
#endif
    if (base == nullptr) {
      set_error("BestFitArena: out of host memory growing by " +
                std::to_string(sz));
      return false;
    }
    chunks_.push_back({base, sz});
    reserved_ += sz;
    free_.Add(static_cast<char *>(base), sz);
    return true;
  }

  ptpu::Mutex mu_{kLockRtArena};
  size_t chunk_size_, align_;
  size_t in_use_ = 0, peak_ = 0, reserved_ = 0;
  std::vector<Chunk> chunks_;
  ptpu::BestFitFreeList<char *> free_;
  std::map<void *, size_t> allocated_;
};

}  // namespace

// Handle-taking entries guard NULL: the ABI is driven from ctypes,
// where a failed create or a teardown race can hand a null back — a
// defined error return beats a segfault (tools/ptpu_check.py lints
// every handle entry for this).
PTPU_EXPORT void *ptpu_arena_create(uint64_t chunk_size, uint64_t alignment) {
  return new BestFitArena(chunk_size, alignment ? alignment : 64);
}
PTPU_EXPORT void ptpu_arena_destroy(void *a) {
  if (!a) return;
  delete static_cast<BestFitArena *>(a);
}
PTPU_EXPORT void *ptpu_arena_alloc(void *a, uint64_t n) {
  if (!a) return nullptr;
  return static_cast<BestFitArena *>(a)->Alloc(n);
}
PTPU_EXPORT int ptpu_arena_free(void *a, void *p) {
  if (!a) return -1;
  return static_cast<BestFitArena *>(a)->Free(p) ? 0 : -1;
}
PTPU_EXPORT uint64_t ptpu_arena_in_use(void *a) {
  if (!a) return 0;
  return static_cast<BestFitArena *>(a)->InUse();
}
PTPU_EXPORT uint64_t ptpu_arena_peak(void *a) {
  if (!a) return 0;
  return static_cast<BestFitArena *>(a)->Peak();
}
PTPU_EXPORT uint64_t ptpu_arena_reserved(void *a) {
  if (!a) return 0;
  return static_cast<BestFitArena *>(a)->Reserved();
}

// ---------------------------------------------------------------------------
// BlockingQueue — bounded MPMC queue of opaque 64-bit tokens.
// Python producers stage batches (kept alive in a Python-side registry) and
// push their tokens; the consumer thread pops. close() wakes everyone.
// ---------------------------------------------------------------------------
namespace {

class BlockingQueue {
 public:
  explicit BlockingQueue(size_t cap) : cap_(cap) {}

  // returns 0 ok, -1 closed, -2 timeout
  int Push(int64_t v, int timeout_ms) {
    ptpu::UniqueLock l(mu_);
    if (!WaitFor(l, timeout_ms, [&] { return closed_ || q_.size() < cap_; }))
      return -2;
    if (closed_) return -1;
    q_.push_back(v);
    cv_.notify_all();
    return 0;
  }

  int Pop(int64_t *out, int timeout_ms) {
    ptpu::UniqueLock l(mu_);
    if (!WaitFor(l, timeout_ms, [&] { return !q_.empty() || closed_; }))
      return -2;
    if (q_.empty()) return -1;  // closed and drained
    *out = q_.front();
    q_.pop_front();
    cv_.notify_all();
    return 0;
  }

  void Close() {
    ptpu::MutexLock g(mu_);
    closed_ = true;
    cv_.notify_all();
  }

  size_t Size() {
    ptpu::MutexLock g(mu_);
    return q_.size();
  }

 private:
  template <class Pred>
  bool WaitFor(ptpu::UniqueLock &l, int timeout_ms, Pred pred) {
    if (timeout_ms < 0) {
      cv_.wait(l, pred);
      return true;
    }
    return ptpu::CvWaitForUs(cv_, l, int64_t(timeout_ms) * 1000, pred);
  }

  ptpu::Mutex mu_{kLockRtQueue};
  ptpu::CondVar cv_;
  std::deque<int64_t> q_;
  size_t cap_;
  bool closed_ = false;
};

}  // namespace

PTPU_EXPORT void *ptpu_queue_create(uint64_t capacity) {
  return new BlockingQueue(capacity);
}
PTPU_EXPORT void ptpu_queue_destroy(void *q) {
  if (!q) return;
  delete static_cast<BlockingQueue *>(q);
}
PTPU_EXPORT int ptpu_queue_push(void *q, int64_t v, int timeout_ms) {
  if (!q) return -1;
  return static_cast<BlockingQueue *>(q)->Push(v, timeout_ms);
}
PTPU_EXPORT int ptpu_queue_pop(void *q, int64_t *out, int timeout_ms) {
  if (!q || !out) return -1;
  return static_cast<BlockingQueue *>(q)->Pop(out, timeout_ms);
}
PTPU_EXPORT void ptpu_queue_close(void *q) {
  if (!q) return;
  static_cast<BlockingQueue *>(q)->Close();
}
PTPU_EXPORT uint64_t ptpu_queue_size(void *q) {
  if (!q) return 0;
  return static_cast<BlockingQueue *>(q)->Size();
}

// ---------------------------------------------------------------------------
// Profiler — scoped host events, chrome-trace JSON export.
// ---------------------------------------------------------------------------
namespace {

struct Event {
  std::string name;
  int64_t ts_us;   // begin
  int64_t dur_us;  // duration
  uint64_t tid;
};

class Profiler {
 public:
  static Profiler &Get() {
    static Profiler p;
    return p;
  }

  void Enable() { enabled_.store(true); }
  void Disable() { enabled_.store(false); }
  bool Enabled() const { return enabled_.load(std::memory_order_relaxed); }

  int64_t NowUs() const {
    return std::chrono::duration_cast<std::chrono::microseconds>(
               std::chrono::steady_clock::now().time_since_epoch())
        .count();
  }

  void Record(const char *name, int64_t begin_us, int64_t end_us) {
    if (!Enabled()) return;
    std::hash<std::thread::id> h;
    Event e{name, begin_us, end_us - begin_us,
            static_cast<uint64_t>(h(std::this_thread::get_id()) & 0xffff)};
    ptpu::MutexLock g(mu_);
    events_.push_back(std::move(e));
  }

  static std::string JsonEscape(const std::string &s) {
    std::string out;
    out.reserve(s.size() + 8);
    for (unsigned char c : s) {
      switch (c) {
        case '"': out += "\\\""; break;
        case '\\': out += "\\\\"; break;
        case '\n': out += "\\n"; break;
        case '\r': out += "\\r"; break;
        case '\t': out += "\\t"; break;
        default:
          if (c < 0x20) {
            char buf[8];
            std::snprintf(buf, sizeof(buf), "\\u%04x", c);
            out += buf;
          } else {
            out += static_cast<char>(c);
          }
      }
    }
    return out;
  }

  int Dump(const char *path) {
    ptpu::MutexLock g(mu_);
    FILE *f = std::fopen(path, "w");
    if (!f) {
      set_error(std::string("profiler: cannot open ") + path);
      return -1;
    }
    std::fputs("{\"traceEvents\":[", f);
    for (size_t i = 0; i < events_.size(); ++i) {
      const Event &e = events_[i];
      std::fprintf(f,
                   "%s{\"name\":\"%s\",\"ph\":\"X\",\"pid\":0,\"tid\":%llu,"
                   "\"ts\":%lld,\"dur\":%lld}",
                   i ? "," : "", JsonEscape(e.name).c_str(),
                   (unsigned long long)e.tid, (long long)e.ts_us,
                   (long long)e.dur_us);
    }
    std::fputs("]}", f);
    std::fclose(f);
    return 0;
  }

  void Clear() {
    ptpu::MutexLock g(mu_);
    events_.clear();
  }

  uint64_t Count() {
    ptpu::MutexLock g(mu_);
    return events_.size();
  }

 private:
  std::atomic<bool> enabled_{false};
  ptpu::Mutex mu_{kLockRtProfiler};
  std::vector<Event> events_;
};

}  // namespace

PTPU_EXPORT void ptpu_profiler_enable() { Profiler::Get().Enable(); }
PTPU_EXPORT void ptpu_profiler_disable() { Profiler::Get().Disable(); }
// cheap on/off probe — the predictor's RecordEvent hook gates per-op
// span emission on it (core/native.py passes this fn's address to
// ptpu_predictor_set_profiler)
PTPU_EXPORT int ptpu_profiler_enabled() {
  return Profiler::Get().Enabled() ? 1 : 0;
}
PTPU_EXPORT int64_t ptpu_profiler_now_us() { return Profiler::Get().NowUs(); }
PTPU_EXPORT void ptpu_profiler_record(const char *name, int64_t begin_us,
                                      int64_t end_us) {
  Profiler::Get().Record(name, begin_us, end_us);
}
PTPU_EXPORT int ptpu_profiler_dump(const char *path) {
  return Profiler::Get().Dump(path);
}
PTPU_EXPORT void ptpu_profiler_clear() { Profiler::Get().Clear(); }
PTPU_EXPORT uint64_t ptpu_profiler_count() { return Profiler::Get().Count(); }

// ---------------------------------------------------------------------------
// Monitor — named int64 stats (platform/monitor.h STAT_ADD).
// ---------------------------------------------------------------------------
namespace {
ptpu::Mutex g_stat_mu{kLockRtStats};
std::map<std::string, int64_t> g_stats;
}  // namespace

PTPU_EXPORT void ptpu_stat_add(const char *name, int64_t v) {
  ptpu::MutexLock g(g_stat_mu);
  g_stats[name] += v;
}
PTPU_EXPORT int64_t ptpu_stat_get(const char *name) {
  ptpu::MutexLock g(g_stat_mu);
  auto it = g_stats.find(name);
  return it == g_stats.end() ? 0 : it->second;
}
PTPU_EXPORT void ptpu_stat_reset(const char *name) {
  ptpu::MutexLock g(g_stat_mu);
  g_stats.erase(name);
}

// ---------------------------------------------------------------------------
// AES-128-CTR — encrypted checkpoint payloads (framework/io/crypto parity).
// Textbook AES implementation; CTR keystream; key = 16 bytes, iv = 16 bytes.
// ---------------------------------------------------------------------------
namespace aes {

static const uint8_t SBOX[256] = {
    0x63, 0x7c, 0x77, 0x7b, 0xf2, 0x6b, 0x6f, 0xc5, 0x30, 0x01, 0x67, 0x2b,
    0xfe, 0xd7, 0xab, 0x76, 0xca, 0x82, 0xc9, 0x7d, 0xfa, 0x59, 0x47, 0xf0,
    0xad, 0xd4, 0xa2, 0xaf, 0x9c, 0xa4, 0x72, 0xc0, 0xb7, 0xfd, 0x93, 0x26,
    0x36, 0x3f, 0xf7, 0xcc, 0x34, 0xa5, 0xe5, 0xf1, 0x71, 0xd8, 0x31, 0x15,
    0x04, 0xc7, 0x23, 0xc3, 0x18, 0x96, 0x05, 0x9a, 0x07, 0x12, 0x80, 0xe2,
    0xeb, 0x27, 0xb2, 0x75, 0x09, 0x83, 0x2c, 0x1a, 0x1b, 0x6e, 0x5a, 0xa0,
    0x52, 0x3b, 0xd6, 0xb3, 0x29, 0xe3, 0x2f, 0x84, 0x53, 0xd1, 0x00, 0xed,
    0x20, 0xfc, 0xb1, 0x5b, 0x6a, 0xcb, 0xbe, 0x39, 0x4a, 0x4c, 0x58, 0xcf,
    0xd0, 0xef, 0xaa, 0xfb, 0x43, 0x4d, 0x33, 0x85, 0x45, 0xf9, 0x02, 0x7f,
    0x50, 0x3c, 0x9f, 0xa8, 0x51, 0xa3, 0x40, 0x8f, 0x92, 0x9d, 0x38, 0xf5,
    0xbc, 0xb6, 0xda, 0x21, 0x10, 0xff, 0xf3, 0xd2, 0xcd, 0x0c, 0x13, 0xec,
    0x5f, 0x97, 0x44, 0x17, 0xc4, 0xa7, 0x7e, 0x3d, 0x64, 0x5d, 0x19, 0x73,
    0x60, 0x81, 0x4f, 0xdc, 0x22, 0x2a, 0x90, 0x88, 0x46, 0xee, 0xb8, 0x14,
    0xde, 0x5e, 0x0b, 0xdb, 0xe0, 0x32, 0x3a, 0x0a, 0x49, 0x06, 0x24, 0x5c,
    0xc2, 0xd3, 0xac, 0x62, 0x91, 0x95, 0xe4, 0x79, 0xe7, 0xc8, 0x37, 0x6d,
    0x8d, 0xd5, 0x4e, 0xa9, 0x6c, 0x56, 0xf4, 0xea, 0x65, 0x7a, 0xae, 0x08,
    0xba, 0x78, 0x25, 0x2e, 0x1c, 0xa6, 0xb4, 0xc6, 0xe8, 0xdd, 0x74, 0x1f,
    0x4b, 0xbd, 0x8b, 0x8a, 0x70, 0x3e, 0xb5, 0x66, 0x48, 0x03, 0xf6, 0x0e,
    0x61, 0x35, 0x57, 0xb9, 0x86, 0xc1, 0x1d, 0x9e, 0xe1, 0xf8, 0x98, 0x11,
    0x69, 0xd9, 0x8e, 0x94, 0x9b, 0x1e, 0x87, 0xe9, 0xce, 0x55, 0x28, 0xdf,
    0x8c, 0xa1, 0x89, 0x0d, 0xbf, 0xe6, 0x42, 0x68, 0x41, 0x99, 0x2d, 0x0f,
    0xb0, 0x54, 0xbb, 0x16};

static const uint8_t RCON[11] = {0x00, 0x01, 0x02, 0x04, 0x08, 0x10,
                                 0x20, 0x40, 0x80, 0x1b, 0x36};

struct Key {
  uint8_t rk[176];  // 11 round keys
};

static void ExpandKey(const uint8_t *key, Key *k) {
  std::memcpy(k->rk, key, 16);
  for (int i = 4; i < 44; ++i) {
    uint8_t t[4];
    std::memcpy(t, k->rk + 4 * (i - 1), 4);
    if (i % 4 == 0) {
      uint8_t tmp = t[0];
      t[0] = SBOX[t[1]] ^ RCON[i / 4];
      t[1] = SBOX[t[2]];
      t[2] = SBOX[t[3]];
      t[3] = SBOX[tmp];
    }
    for (int j = 0; j < 4; ++j)
      k->rk[4 * i + j] = k->rk[4 * (i - 4) + j] ^ t[j];
  }
}

static uint8_t xtime(uint8_t x) {
  return (uint8_t)((x << 1) ^ ((x >> 7) * 0x1b));
}

static void EncryptBlock(const Key &k, const uint8_t in[16],
                         uint8_t out[16]) {
  uint8_t s[16];
  std::memcpy(s, in, 16);
  for (int i = 0; i < 16; ++i) s[i] ^= k.rk[i];
  for (int round = 1; round <= 10; ++round) {
    // SubBytes
    for (int i = 0; i < 16; ++i) s[i] = SBOX[s[i]];
    // ShiftRows
    uint8_t t;
    t = s[1]; s[1] = s[5]; s[5] = s[9]; s[9] = s[13]; s[13] = t;
    t = s[2]; s[2] = s[10]; s[10] = t; t = s[6]; s[6] = s[14]; s[14] = t;
    t = s[15]; s[15] = s[11]; s[11] = s[7]; s[7] = s[3]; s[3] = t;
    // MixColumns (skip on final round)
    if (round != 10) {
      for (int c = 0; c < 4; ++c) {
        uint8_t *col = s + 4 * c;
        uint8_t a0 = col[0], a1 = col[1], a2 = col[2], a3 = col[3];
        uint8_t all = a0 ^ a1 ^ a2 ^ a3;
        col[0] = a0 ^ all ^ xtime(a0 ^ a1);
        col[1] = a1 ^ all ^ xtime(a1 ^ a2);
        col[2] = a2 ^ all ^ xtime(a2 ^ a3);
        col[3] = a3 ^ all ^ xtime(a3 ^ a0);
      }
    }
    // AddRoundKey
    for (int i = 0; i < 16; ++i) s[i] ^= k.rk[16 * round + i];
  }
  std::memcpy(out, s, 16);
}

}  // namespace aes

// CTR mode: identical for encrypt/decrypt.
PTPU_EXPORT int ptpu_aes_ctr_xcrypt(const uint8_t *key16, const uint8_t *iv16,
                                    const uint8_t *in, uint8_t *out,
                                    uint64_t n) {
  aes::Key k;
  aes::ExpandKey(key16, &k);
  uint8_t ctr[16], ks[16];
  std::memcpy(ctr, iv16, 16);
  for (uint64_t off = 0; off < n; off += 16) {
    aes::EncryptBlock(k, ctr, ks);
    uint64_t m = std::min<uint64_t>(16, n - off);
    for (uint64_t i = 0; i < m; ++i) out[off + i] = in[off + i] ^ ks[i];
    // increment big-endian counter
    for (int i = 15; i >= 0; --i)
      if (++ctr[i] != 0) break;
  }
  return 0;
}

// ---------------------------------------------------------------------------
// Numeric data-feed parser (reference: MultiSlotDataFeed /
// InMemoryDataFeed parse hot loop, `framework/data_feed.cc` — the
// reference keeps record parsing native because Python tokenization is
// the bottleneck when LoadIntoMemory streams GBs of slot text).
//
// Parses whitespace-separated numeric lines (one record per line) from a
// NUL-terminated buffer. Two-pass contract: ptpu_feed_count sizes the
// output, ptpu_feed_parse fills caller-allocated arrays.
// ---------------------------------------------------------------------------

PTPU_EXPORT int ptpu_feed_count(const char *buf, int64_t len,
                                int64_t *n_vals, int64_t *n_lines) {
  if (!buf || !n_vals || !n_lines) return -1;
  int64_t vals = 0, lines = 0;
  bool in_tok = false, line_has = false;
  for (int64_t i = 0; i < len; ++i) {
    char c = buf[i];
    if (c == '\n') {
      if (in_tok) { ++vals; in_tok = false; }
      if (line_has) ++lines;
      line_has = false;
    } else if (c == ' ' || c == '\t' || c == '\r' || c == ',') {
      if (in_tok) { ++vals; in_tok = false; }
    } else {
      in_tok = true;
      line_has = true;
    }
  }
  if (in_tok) ++vals;
  if (line_has) ++lines;
  *n_vals = vals;
  *n_lines = lines;
  return 0;
}

PTPU_EXPORT int ptpu_feed_parse(const char *buf, int64_t len, float *vals,
                                int64_t vals_cap, int64_t *line_starts,
                                int64_t lines_cap, int64_t *n_vals_out) {
  if (!buf || !vals || !line_starts || !n_vals_out) return -1;
  const char *p = buf;
  const char *end = buf + len;
  int64_t nv = 0, nl = 0;
  bool line_open = false;
  while (p < end && *p) {
    char c = *p;
    if (c == '\n') {
      line_open = false;
      ++p;
      continue;
    }
    if (c == ' ' || c == '\t' || c == '\r' || c == ',') {
      ++p;
      continue;
    }
    if (!line_open) {
      if (nl >= lines_cap) return -2;
      line_starts[nl++] = nv;
      line_open = true;
    }
    char *tok_end = nullptr;
    float v = std::strtof(p, &tok_end);
    if (tok_end == p) return -3;  // non-numeric token
    if (nv >= vals_cap) return -2;
    vals[nv++] = v;
    p = tok_end;
  }
  // callers MUST verify n_vals_out against ptpu_feed_count's tally: an
  // early stop (embedded NUL, locale surprises) would otherwise leave
  // the tail of the caller's buffer uninitialized
  *n_vals_out = nv;
  return static_cast<int>(nl);
}

PTPU_EXPORT const char *ptpu_version() { return "paddle_tpu-native 0.1"; }
