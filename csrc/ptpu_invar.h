// ptpu_invar — declarative counter-conservation invariants over the
// stats snapshots BOTH native servers export (ISSUE 20).
//
// Every subsystem's acceptance gate in this repo is some flavor of
// "counters exact": serving_bench, decode_bench, ps_bench, the drill
// chaos soak and a dozen C selftests each re-derived their own
// reconciliation arithmetic by hand. ptpu_invar makes the counter
// algebra itself a first-class, machine-checked artifact:
//
//   * ONE manifest (kInvarManifest below) declares the conservation
//     laws and binds every participating counter to the C++ member
//     expression that bumps it and the TU it lives in;
//   * the `invar` checker in tools/ptpu_check.py enforces the
//     manifest STATICALLY — every declared flow edge has a bump
//     site, error paths bump their paired term, no bound counter is
//     bumped at a site the manifest doesn't account for, and the
//     Python twin string (profiler/stats.py INVAR_MANIFEST) stays
//     token-identical;
//   * this engine enforces it AT RUNTIME: CheckJson() evaluates the
//     laws over a stats snapshot (the *_stats_json strings), parsed
//     with the same restricted JSON walker /metrics uses
//     (ptpu_trace.h rj:: — fuzzed by csrc/fuzz/fuzz_json.cc), wired
//     into both servers' Stop(), the C selftest teardowns, the bench
//     guards, GET /invarz, and drill_replay.py's chaos soak.
//
// Quiesce semantics: `==` laws only hold when no request is in
// flight — a snapshot taken mid-request can legitimately see
// `requests == replies + 1`. The gates therefore run at quiesce
// points (Stop() after drain, selftest teardown, bench end, soak
// drain); GET /invarz is served any time but is informational while
// traffic flows. `>=` laws hold at any instant the snapshot is
// internally consistent.
//
// Kill switch: PTPU_INVAR_OFF=1 turns every gate into a no-op (the
// report says "enabled":false and carries zero violations) — the
// escape hatch if a deployment hits a law the manifest got wrong.
//
// Manifest grammar (one declaration per line, '#' comments):
//
//   counter <planes> <path> <file> <expr>
//       A monotonic counter: JSON leaf <path> (dot-joined) in the
//       snapshot of each plane in <planes> (comma list of
//       serving|ps), bumped ONLY inside <file> (comma list of
//       repo-relative TUs) via `<expr>.Add(`.
//   gauge <planes> <path> <file> <expr>
//       A level, not a flow: computed or +/- adjusted; exempt from
//       the bump-site rules, but <expr> must appear in <file> and
//       <path> must be rendered there.
//   invar <planes> <name> <path> ==|>= <path> [+ <path> ...]
//       A conservation law over bound paths. Laws whose left-hand
//       path is absent from a snapshot are skipped (optional
//       subsystems: the decode block only exists with a decode
//       plan); a law whose LHS resolves but an RHS term doesn't is a
//       violation.
//   pair <file> <exprA> <exprB>
//       Per-function flow discipline: any function body in <file>
//       bumping <exprA> must also touch <exprB> (the nullcheck-style
//       path rule — catches an error path that bumps the success
//       side without its paired term).
//
// The manifest string below and profiler/stats.py::INVAR_MANIFEST are
// twins — token-identical, enforced by the `invar` checker — so the
// Python evaluator needs neither codegen nor a csrc/ checkout.
#ifndef PTPU_INVAR_H_
#define PTPU_INVAR_H_

#include <string>

namespace ptpu {
namespace invar {

// The single source of truth for the counter algebra. Adding a
// counter to a conservation law? Bind it here (and in the Python
// twin), then `python3 tools/ptpu_check.py --check invar` tells you
// every site the binding misses. See README "Correctness tooling v4".
inline const char* Manifest() {
  return R"INV(# ptpu_invar manifest — counter conservation laws (twin: profiler/stats.py)

# ---- serving + PS shared net plane (csrc/ptpu_net.cc) ----
counter serving,ps server.conns_accepted csrc/ptpu_net.cc stats_->conns_accepted
counter serving,ps server.conns_closed csrc/ptpu_net.cc stats_->conns_closed
counter serving,ps server.handshake_fails csrc/ptpu_net.cc stats_->handshake_fails
counter serving,ps server.handshake_timeouts csrc/ptpu_net.cc stats_->handshake_timeouts
gauge serving,ps server.conns_active csrc/ptpu_net.cc active_conns

# every framed conn accepted is either still active or was closed —
# exact because accept pairs accepted++ with active++ and FinishClose
# pairs closed++ with active-- (telemetry HTTP conns are exempt and
# uncounted on both sides)
invar serving,ps conn_balance server.conns_accepted == server.conns_active + server.conns_closed
# handshake failures/timeouts are close reasons of counted conns
# (idle_closes is NOT listed: HTTP conns may idle-close uncounted)
invar serving,ps close_reasons server.conns_closed >= server.handshake_fails + server.handshake_timeouts

# ---- serving request plane (csrc/ptpu_serving.cc) ----
counter serving server.requests csrc/ptpu_serving.cc stats.requests
counter serving server.replies csrc/ptpu_serving.cc stats.replies
counter serving server.req_errors csrc/ptpu_serving.cc stats.req_errors
counter serving server.op_errors csrc/ptpu_serving.cc stats.op_errors
counter serving server.err_frames csrc/ptpu_serving.cc stats.err_frames
# the PS data plane reuses the err_frames name for its own ledger
counter ps server.err_frames csrc/ptpu_ps_server.cc stats.err_frames

# the zero-stuck-requests proof: every accepted INFER request is
# answered exactly once — a reply or an error frame (replies are
# counted at send-decision time, so a killed conn still balances;
# decode/meta op errors land in op_errors, not here)
invar serving req_balance server.requests == server.replies + server.req_errors
# every ERR frame is attributed to exactly one plane: INFER
# (req_errors) or decode/meta op (op_errors) — proto errors close
# the conn without an ERR frame and count in neither
invar serving err_split server.err_frames == server.req_errors + server.op_errors
pair csrc/ptpu_serving.cc stats.req_errors stats.err_frames
pair csrc/ptpu_serving.cc stats.op_errors stats.err_frames

# ---- decode session ledger (csrc/ptpu_serving.cc, dstats) ----
counter serving decode.opens csrc/ptpu_serving.cc dstats.opens
counter serving decode.closes csrc/ptpu_serving.cc dstats.closes
counter serving decode.evictions csrc/ptpu_serving.cc dstats.evictions
counter serving decode.hibernates csrc/ptpu_serving.cc dstats.hibernates
counter serving decode.restores csrc/ptpu_serving.cc dstats.restores
counter serving decode.forks csrc/ptpu_serving.cc dstats.forks
gauge serving decode.sessions_active csrc/ptpu_serving.cc sessions_active
gauge serving decode.sessions_hibernated csrc/ptpu_serving.cc sessions_hibernated

# every session ever opened is live, hibernated, or exited exactly
# once as a close or an eviction (tombstones count at eviction time;
# closing a tombstone later is NOT a second exit)
invar serving session_balance decode.opens == decode.closes + decode.evictions + decode.sessions_active + decode.sessions_hibernated
invar serving hibernate_flow decode.hibernates >= decode.restores
# a fork IS an open (fork path bumps both)
invar serving forks_are_opens decode.opens >= decode.forks
pair csrc/ptpu_serving.cc dstats.forks dstats.opens

# ---- KV pool page + hibernation ledgers (csrc/ptpu_predictor.cc) ----
gauge serving decode.pool.pages_total csrc/ptpu_predictor.cc npages_
gauge serving decode.pool.pages_in_use csrc/ptpu_predictor.cc npages_
gauge serving decode.pool.pages_free csrc/ptpu_predictor.cc free_
gauge serving decode.pool.pages_cached csrc/ptpu_predictor.cc pages_cached
gauge serving decode.pool.sessions_hibernated csrc/ptpu_predictor.cc hib_
counter serving decode.pool.hibernates csrc/ptpu_predictor.cc hibernates_
counter serving decode.pool.restores csrc/ptpu_predictor.cc restores_
counter serving decode.pool.hib_drops csrc/ptpu_predictor.cc hib_drops_
gauge serving decode.pool.spill_slots_total csrc/ptpu_predictor.cc slots_total
gauge serving decode.pool.spill_slots_in_use csrc/ptpu_predictor.cc slots_in_use

# page conservation: the pool never leaks or invents a page —
# rendered under one mu_ hold, so this is exact at ANY instant
invar serving page_balance decode.pool.pages_total == decode.pool.pages_in_use + decode.pool.pages_free
# cached (published, ref==1) pages are a subset of in-use pages
invar serving cache_subset decode.pool.pages_in_use >= decode.pool.pages_cached
# every hibernation record ever created was restored, dropped, or is
# still resident in the registry — exact under mu_
invar serving pool_hib_balance decode.pool.hibernates == decode.pool.restores + decode.pool.hib_drops + decode.pool.sessions_hibernated
invar serving spill_slots decode.pool.spill_slots_total >= decode.pool.spill_slots_in_use
)INV";
}

// Evaluate every law against `stats_json` (a *_stats_json snapshot).
// `plane` is "serving", "ps", or "auto" (sniffed from the snapshot
// shape: a batcher section means serving). Returns the report JSON —
// deliberately inside the restricted rj:: grammar (no booleans, no
// object arrays) so the same fuzzed walker consumes its own verdicts:
//   {"enabled":1,"plane":"serving","checked":N,"skipped":N,
//    "violations":{<law-name>:{"law":...,"detail":...},...}}
// Unparseable snapshots report one "snapshot" violation. When
// PTPU_INVAR_OFF=1 the report is {"enabled":0,...} with zero
// violations — the kill switch for a mis-declared law.
std::string CheckJson(const std::string& stats_json,
                      const std::string& plane);

// Number of violations inside a CheckJson() report (-1 when the
// report itself doesn't parse). The selftest-teardown helper.
int ViolationCount(const std::string& report);

// Teardown gate: evaluate and, on any violation, print the report to
// stderr and return the violation count (0 when clean or killed via
// PTPU_INVAR_OFF=1). Both servers' Stop() call this, so every C
// selftest teardown and bench shutdown inherits the gate; with
// PTPU_INVAR_FATAL=1 (set by the selftests and bench guards) a
// violation abort()s instead of merely reporting.
int GateQuiesced(const std::string& stats_json,
                 const std::string& plane, const char* where);

}  // namespace invar
}  // namespace ptpu

extern "C" {
/* Evaluate the conservation-law manifest over a stats snapshot.
 * `plane` is "serving", "ps" or "auto"/NULL. Returns the report JSON
 * (see ptpu::invar::CheckJson); pointer valid until the next call on
 * this thread. */
const char* ptpu_invar_check_json(const char* stats_json,
                                  const char* plane);
/* The manifest text itself (twin-checked against profiler/stats.py —
 * lets tooling assert parity against a live .so, not a checkout). */
const char* ptpu_invar_manifest(void);
}

#endif  // PTPU_INVAR_H_
