// ptpu_trace — lock-free sampled per-request span recorder shared by
// BOTH native servers (csrc/ptpu_ps_server.cc, csrc/ptpu_serving.cc)
// and the net core (csrc/ptpu_net.cc records the reply-flush span).
// Reference counterpart: the profiler/timeline layer the upstream
// stack pairs with its executor (platform/profiler RecordEvent ->
// chrome trace) plus the /tracez-style request sampling every
// production RPC layer grows (brpc rpcz).
//
// Shape:
//   * A fixed-slot ring of COMPLETED span records. A writer claims a
//     slot with one relaxed fetch_add and publishes begin/end
//     microseconds, kind, conn id and a kind-specific arg (batch id,
//     session id, request id) through relaxed atomics — zero
//     allocation, zero locks, no syscalls on the hot path. Readers
//     (GET /tracez) snapshot the ring and drop torn slots by sequence
//     check; tracing is observability, not an audit log.
//   * Sampling: PTPU_TRACE_SAMPLE = 0 disables everything (the
//     zero-cost path: one relaxed load per request), 1 traces every
//     request, N traces 1-in-N. A client-supplied trace id (the v2
//     wire frames) is always traced while sampling is on — explicit
//     opt-in wins over the sampling dice.
//   * Slow-request ring: any request whose end-to-end latency crosses
//     PTPU_TRACE_SLOW_US (0 = off) gets its FULL span breakdown
//     captured into a small bounded ring, sampled or not — the "why
//     was that one INFER slow" answer survives even at 1-in-N
//     sampling.
//
// One Recorder instance per loaded .so (Global()); servers in the same
// process but different shared objects each own their ring. The
// runtime override ptpu_trace_set(sample, slow_us) and the JSON view
// TracezJson() are exported through each server's ABI/HTTP endpoint.
//
// Span-kind names must stay identical to the Python timeline map
// (paddle_tpu/profiler/timeline.py SPAN_KIND_NAMES) — the `trace`
// checker in tools/ptpu_check.py holds the two in lockstep.
#ifndef PTPU_TRACE_H_
#define PTPU_TRACE_H_

#include <atomic>
#include <cstdint>
#include <string>
#include <utility>
#include <vector>

namespace ptpu {
namespace trace {

// Lifecycle span kinds. Index == wire value in /tracez; names in
// kSpanKindNames (ptpu_trace.cc) == timeline.py SPAN_KIND_NAMES.
enum Kind : uint8_t {
  kRead = 0,    // frame bytes first seen -> dispatched to the server
  kQueue = 1,   // request enqueued -> popped by a batcher worker
  kBatch = 2,   // batch popped -> inputs stitched, run ready
  kRun = 3,     // predictor run (one batch)
  kFlush = 4,   // reply queued on the conn -> last byte written
  kPull = 5,    // PS pull handled (parse -> reply queued)
  kPush = 6,    // PS push handled (parse -> ack queued)
  kDecode = 7,  // decode step run (one continuous-batching sub-run)
  kKindCount
};

extern const char* const kSpanKindNames[kKindCount];

// The trace-id extension of v2 wire frames: [ver=2][tag][u64 trace id]
// then the v1 body. Python twins: TRACE_EXT in inference/serving.py
// and distributed/ps/wire.py (trace checker parity).
constexpr uint32_t kTraceExt = 8;

struct Config {
  int64_t sample = 64;        // PTPU_TRACE_SAMPLE: 0 off, 1 all, N 1-in-N
  int64_t slow_us = 100000;   // PTPU_TRACE_SLOW_US: 0 off
  size_t ring = 4096;         // PTPU_TRACE_RING span slots (pow2-rounded)
  size_t slow_ring = 64;      // slow-request slots (pow2-rounded)
};

Config ConfigFromEnv();

// A completed span, as read back out of the ring.
struct SpanView {
  uint64_t trace_id = 0;
  uint8_t kind = 0;
  int64_t t0_us = 0, t1_us = 0;
  uint64_t conn = 0;  // net-core connection id
  uint64_t arg = 0;   // kind-specific: batch seq / session / req id
};

// Caller-side span scratch for RecordSlow (stack array, no alloc).
struct SpanRec {
  uint8_t kind = 0;
  int64_t t0_us = 0, t1_us = 0;
};

struct SlowView {
  uint64_t trace_id = 0, conn = 0, req = 0;
  int64_t e2e_us = 0;
  std::vector<SpanView> spans;
};

class Recorder {
 public:
  static constexpr int kSlowSpans = 8;

  explicit Recorder(const Config& cfg);

  // Sampling decision for one arriving request. Returns the effective
  // trace id (client id, or a fresh one when the sampling dice hit),
  // or 0 = not traced. With sample == 0 this is ONE relaxed load.
  uint64_t BeginRequest(uint64_t client_tid) {
    const int64_t s = sample_.load(std::memory_order_relaxed);
    if (s <= 0) return 0;
    if (client_tid) return client_tid;
    if (s != 1 &&
        sample_ctr_.fetch_add(1, std::memory_order_relaxed) %
                uint64_t(s) !=
            0)
      return 0;
    return NewTraceId();
  }

  // Record one completed span. tid == 0 is a no-op (untraced request).
  void Record(uint64_t tid, uint8_t kind, int64_t t0_us, int64_t t1_us,
              uint64_t conn, uint64_t arg);

  bool SlowEligible(int64_t e2e_us) const {
    const int64_t t = slow_us_.load(std::memory_order_relaxed);
    return t > 0 && e2e_us >= t;
  }

  // Capture a slow request's full breakdown (first kSlowSpans spans).
  void RecordSlow(uint64_t tid, uint64_t conn, uint64_t req,
                  int64_t e2e_us, const SpanRec* spans, int n);

  // Runtime override (ptpu_trace_set ABI): sample < 0 / slow_us < 0
  // keep the current value.
  void Set(int64_t sample, int64_t slow_us);

  int64_t sample() const {
    return sample_.load(std::memory_order_relaxed);
  }
  int64_t slow_us() const {
    return slow_us_.load(std::memory_order_relaxed);
  }
  uint64_t recorded() const {
    return head_.load(std::memory_order_relaxed);
  }
  size_t ring_size() const { return ring_.size(); }

  // Newest-first snapshots; torn slots (mid-overwrite) are skipped.
  void Snapshot(std::vector<SpanView>* out, size_t max_n) const;
  void SnapshotSlow(std::vector<SlowView>* out) const;

  // {"sample","slow_us","ring","recorded","spans":[...],"slow":[...]}
  // — the GET /tracez body.
  std::string TracezJson(size_t max_n) const;

 private:
  struct Slot {
    std::atomic<uint64_t> seq{0};  // 2*idx+1 writing, 2*idx+2 done
    std::atomic<uint64_t> trace_id{0}, conn{0}, arg{0};
    std::atomic<int64_t> t0{0}, t1{0};
    std::atomic<uint8_t> kind{0};
  };
  struct SlowSlot {
    std::atomic<uint64_t> seq{0};
    std::atomic<uint64_t> trace_id{0}, conn{0}, req{0};
    std::atomic<int64_t> e2e{0};
    std::atomic<int32_t> n{0};
    std::atomic<uint8_t> kind[kSlowSpans] = {};
    std::atomic<int64_t> t0[kSlowSpans] = {}, t1[kSlowSpans] = {};
  };

  uint64_t NewTraceId();

  std::atomic<int64_t> sample_, slow_us_;
  std::atomic<uint64_t> head_{0}, slow_head_{0};
  std::atomic<uint64_t> sample_ctr_{0}, id_ctr_{0};
  uint64_t seed_;
  std::vector<Slot> ring_;       // size is a power of two
  std::vector<SlowSlot> slow_;   // size is a power of two
};

// Process-global recorder for this shared object, lazily constructed
// from the PTPU_TRACE_* env on first touch.
Recorder& Global();

// ---------------------------------------------------------------------------
// Prometheus exposition renderer (GET /metrics). Walks a stats JSON
// snapshot (the exact strings the servers' *_stats_json render) and
// emits the same text profiler/stats.py::prometheus_text produces for
// that snapshot — byte-for-byte (tested): nested keys join the metric
// name with '_', a "tables" level becomes a table="<name>" label,
// histograms render cumulative le-bucket _bucket/_sum/_count series,
// each family gets exactly one "# TYPE" line.
// ---------------------------------------------------------------------------
std::string PromFromStatsJson(const std::string& stats_json,
                              const std::string& prefix);

// ---------------------------------------------------------------------------
// Restricted JSON reader — the walker behind PromFromStatsJson, shared
// with the ptpu_invar conservation-law engine (csrc/ptpu_invar.cc).
// Parses exactly the grammar OUR renderers emit: objects, unsigned
// integers, arrays of unsigned integers, escaped strings. Header-only
// so every single-TU selftest and fuzz harness (csrc/fuzz/fuzz_json.cc
// keeps this walker under coverage-guided fuzzing) compiles the same
// code the shipping .so's run.
// ---------------------------------------------------------------------------
namespace rj {

struct JNode {
  enum Kind { kNum, kStr, kArr, kObj } kind = kNum;
  uint64_t num = 0;
  std::string str;
  std::vector<uint64_t> arr;
  std::vector<std::pair<std::string, JNode>> obj;  // insertion order
};

struct JParser {
  const char* p;
  const char* end;
  bool ok = true;

  void Ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' ||
                       *p == '\r'))
      ++p;
  }

  bool Eat(char c) {
    Ws();
    if (p < end && *p == c) {
      ++p;
      return true;
    }
    ok = false;
    return false;
  }

  std::string Str() {
    std::string s;
    if (!Eat('"')) return s;
    while (p < end && *p != '"') {
      if (*p == '\\' && p + 1 < end) {
        ++p;
        switch (*p) {
          case 'n': s += '\n'; break;
          case 't': s += '\t'; break;
          case 'r': s += '\r'; break;
          case '"': s += '"'; break;
          case '\\': s += '\\'; break;
          default: s += *p; break;  // \uXXXX never emitted for names
        }
        ++p;
      } else {
        s += *p++;
      }
    }
    if (p < end) ++p;  // closing quote
    else ok = false;
    return s;
  }

  uint64_t Num() {
    Ws();
    uint64_t v = 0;
    bool any = false;
    while (p < end && *p >= '0' && *p <= '9') {
      v = v * 10 + uint64_t(*p - '0');
      ++p;
      any = true;
    }
    if (!any) ok = false;
    return v;
  }

  JNode Value(int depth) {
    JNode n;
    Ws();
    if (!ok || depth > 16 || p >= end) {
      ok = false;
      return n;
    }
    if (*p == '{') {
      ++p;
      n.kind = JNode::kObj;
      Ws();
      if (p < end && *p == '}') {
        ++p;
        return n;
      }
      for (;;) {
        std::string k = Str();
        if (!Eat(':')) break;
        n.obj.emplace_back(std::move(k), Value(depth + 1));
        Ws();
        if (p < end && *p == ',') {
          ++p;
          continue;
        }
        Eat('}');
        break;
      }
      return n;
    }
    if (*p == '[') {
      ++p;
      n.kind = JNode::kArr;
      Ws();
      if (p < end && *p == ']') {
        ++p;
        return n;
      }
      for (;;) {
        n.arr.push_back(Num());
        Ws();
        if (p < end && *p == ',') {
          ++p;
          continue;
        }
        Eat(']');
        break;
      }
      return n;
    }
    if (*p == '"') {
      n.kind = JNode::kStr;
      n.str = Str();
      return n;
    }
    n.kind = JNode::kNum;
    n.num = Num();
    return n;
  }
};

inline bool IsHist(const JNode& n) {
  if (n.kind != JNode::kObj) return false;
  bool c = false, s = false, b = false;
  for (const auto& kv : n.obj) {
    if (kv.first == "count") c = true;
    else if (kv.first == "sum") s = true;
    else if (kv.first == "buckets") b = true;
  }
  return c && s && b;
}

inline const JNode* HistField(const JNode& n, const char* name) {
  for (const auto& kv : n.obj)
    if (kv.first == name) return &kv.second;
  return nullptr;
}

}  // namespace rj

}  // namespace trace
}  // namespace ptpu

#endif  // PTPU_TRACE_H_
