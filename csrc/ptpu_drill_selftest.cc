// Native unit tests for the production-drill harness (ptpu_capture.h
// + the PTPU_CHAOS fault-injection sites in ptpu_net.cc) — the
// cc_test analogue, same harness idiom as the other selftests (plain
// asserts, exit 0 = pass; run by `make selftest` and both sancheck
// legs; wrapped by tests/test_native_selftest.py).
//
// Covered: capture-file parser whole-file reject family + round trip,
// capture ring wraparound EXACTNESS (newest-first snapshot of the
// last ring_size frames, byte-for-byte), payload truncation at
// cap_bytes, 1-in-N sampling dice, SaveFile -> ParseCaptureBytes
// round trip, the GET /capturez route over a live echo server with
// runtime Set() on/off, and every chaos kind: injected conn kills
// mapping 1:1 to client-observed deaths, handshake drops counted as
// handshake_fails, read/write delays staying lossless, and short
// writes delivering intact replies through the partial-write path.
#include "ptpu_net.cc"
#include "ptpu_trace.cc"

// asserts ARE the test — never compile them out
#undef NDEBUG
#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>

#include <cassert>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

using ptpu::HmacSha256;
using ptpu::PutU32;
using ptpu::ReadExact;
using ptpu::WriteExact;
using ptpu::net::Callbacks;
using ptpu::net::ConnPtr;
using ptpu::net::FrameResult;
using ptpu::net::Options;
using ptpu::net::Server;
using ptpu::net::Stats;
namespace cap = ptpu::capture;

namespace {

// ------------------------------------------------------ client side

int dial(int port) {
  const int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  assert(fd >= 0);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons(uint16_t(port));
  assert(::connect(fd, reinterpret_cast<sockaddr *>(&addr),
                   sizeof(addr)) == 0);
  return fd;
}

bool client_handshake(int fd, const std::string &key) {
  uint8_t nonce[16];
  if (!ReadExact(fd, nonce, 16)) return false;
  uint8_t mac[32];
  HmacSha256(reinterpret_cast<const uint8_t *>(key.data()), key.size(),
             nonce, 16, mac);
  uint8_t frame[36];
  PutU32(frame, 32);
  std::memcpy(frame + 4, mac, 32);
  if (!WriteExact(fd, frame, 36)) return false;
  uint8_t ok = 0;
  return ReadExact(fd, &ok, 1) && ok == 0x01;
}

void send_frame(int fd, const std::vector<uint8_t> &payload) {
  uint8_t lenb[4];
  PutU32(lenb, uint32_t(payload.size()));
  assert(WriteExact(fd, lenb, 4));
  assert(WriteExact(fd, payload.data(), payload.size()));
}

bool recv_frame(int fd, std::vector<uint8_t> *out) {
  uint8_t lenb[4];
  if (!ReadExact(fd, lenb, 4)) return false;
  out->resize(ptpu::GetU32(lenb));
  return out->empty() || ReadExact(fd, out->data(), out->size());
}

std::string http_get(int port, const std::string &target) {
  const int fd = dial(port);
  const std::string req = "GET " + target +
                          " HTTP/1.1\r\nHost: x\r\n"
                          "Connection: close\r\n\r\n";
  assert(WriteExact(fd, reinterpret_cast<const uint8_t *>(req.data()),
                    req.size()));
  std::string out;
  char buf[4096];
  for (;;) {
    const ssize_t r = ::read(fd, buf, sizeof(buf));
    if (r <= 0) break;
    out.append(buf, size_t(r));
  }
  ::close(fd);
  return out;
}

// ------------------------------------------------------ echo server

struct EchoServer {
  Stats stats;
  std::unique_ptr<Server> srv;

  explicit EchoServer(Options opt) {
    Callbacks cbs;
    cbs.on_frame = [](const ConnPtr &c, const uint8_t *p, uint32_t n) {
      return c->SendCopy(p, n) ? FrameResult::kOk : FrameResult::kClose;
    };
    // the stock telemetry table — the same /capturez the production
    // servers mount
    cbs.on_http = [](const std::string &target) {
      return ptpu::net::TelemetryHttp(
          target, [] { return std::string("{}"); }, "ptpu_test",
          false);
    };
    srv.reset(new Server(opt, std::move(cbs), &stats));
    std::string err;
    if (!srv->Start(&err)) {
      std::fprintf(stderr, "start failed: %s\n", err.c_str());
      assert(false);
    }
  }
};

Options base_opts(const char *key) {
  Options o;
  o.authkey = key;
  o.event_threads = 1;  // one chaos dice: injection order deterministic
  return o;
}

// ------------------------------------------- capture format helpers

std::vector<uint8_t> mk_file(uint32_t magic, uint32_t version,
                             uint32_t count,
                             const std::vector<uint8_t> &body) {
  std::vector<uint8_t> f(cap::kCaptureHeaderBytes + body.size());
  PutU32(f.data(), magic);
  PutU32(f.data() + 4, version);
  PutU32(f.data() + 8, count);
  PutU32(f.data() + 12, uint32_t(body.size()));
  std::memcpy(f.data() + 16, body.data(), body.size());
  return f;
}

std::vector<uint8_t> mk_rec(int64_t ts, uint64_t conn,
                            uint32_t frame_len,
                            const std::vector<uint8_t> &payload,
                            int ver_override = -1,
                            int tag_override = -1,
                            uint16_t reserved = 0) {
  std::vector<uint8_t> r(cap::kCaptureRecBytes + payload.size());
  std::memcpy(r.data(), &ts, 8);
  std::memcpy(r.data() + 8, &conn, 8);
  PutU32(r.data() + 16, frame_len);
  PutU32(r.data() + 20, uint32_t(payload.size()));
  r[24] = ver_override >= 0 ? uint8_t(ver_override)
                            : (payload.size() >= 1 ? payload[0] : 0);
  r[25] = tag_override >= 0 ? uint8_t(tag_override)
                            : (payload.size() >= 2 ? payload[1] : 0);
  std::memcpy(r.data() + 26, &reserved, 2);
  std::memcpy(r.data() + 28, payload.data(), payload.size());
  return r;
}

std::vector<uint8_t> cat(const std::vector<std::vector<uint8_t>> &vs) {
  std::vector<uint8_t> out;
  for (const auto &v : vs) out.insert(out.end(), v.begin(), v.end());
  return out;
}

// ------------------------------------------------------------ tests

void test_capture_parse_reject_family() {
  const std::vector<uint8_t> p1 = {0x01, 0x60, 'a', 'b'};
  const std::vector<uint8_t> p2 = {0x01, 0x63};
  auto good = mk_file(cap::kCaptureMagic, cap::kCaptureVersion, 2,
                      cat({mk_rec(100, 7, 4, p1),
                           mk_rec(200, 8, 9, p2)}));
  std::vector<cap::CapRecord> out;
  assert(cap::ParseCaptureBytes(good.data(), good.size(), &out) ==
         cap::ParseResult::kOk);
  assert(out.size() == 2);
  assert(out[0].ts_us == 100 && out[0].conn == 7 &&
         out[0].frame_len == 4 && out[0].ver == 1 &&
         out[0].tag == 0x60 && out[0].payload == p1);
  assert(out[1].frame_len == 9 && out[1].payload == p2);

  // serialize twin reproduces the same bytes
  std::vector<uint8_t> rt;
  cap::SerializeCapture(out, &rt);
  assert(rt == good);

  // the whole-file reject family: every malformed shape returns
  // kMalformed and leaves *out untouched
  auto expect_reject = [](std::vector<uint8_t> f) {
    std::vector<cap::CapRecord> scratch = {cap::CapRecord{}};
    assert(cap::ParseCaptureBytes(f.data(), f.size(), &scratch) ==
           cap::ParseResult::kMalformed);
    assert(scratch.size() == 1);  // full reject never partially adopts
  };
  expect_reject({good.begin(), good.begin() + 11});  // short header
  auto bad = good;
  bad[0] ^= 1;
  expect_reject(bad);  // magic
  bad = good;
  bad[4] = 9;
  expect_reject(bad);  // version
  bad = good;
  PutU32(bad.data() + 8, cap::kCaptureMaxRecords + 1);
  expect_reject(bad);  // count over cap
  bad = good;
  bad.push_back(0);
  expect_reject(bad);  // size != header + body
  bad = good;
  bad.pop_back();
  expect_reject(bad);  // truncated payload
  bad = good;
  PutU32(bad.data() + 16 + 20, 500);
  expect_reject(bad);  // cap_len > frame_len
  bad = good;
  bad[16 + 26] = 1;
  expect_reject(bad);  // reserved != 0
  bad = good;
  bad[16 + 24] = 9;
  expect_reject(bad);  // ver field != payload[0]
  bad = good;
  bad[16 + 25] = 0x99;
  expect_reject(bad);  // tag field != payload[1]
  expect_reject(mk_file(cap::kCaptureMagic, cap::kCaptureVersion, 3,
                        cat({mk_rec(1, 1, 4, p1)})));  // count lies
  assert(cap::ParseCaptureBytes(nullptr, 0, &out) ==
         cap::ParseResult::kMalformed);
}

void test_ring_wraparound_exact() {
  cap::Config cfg;
  cfg.sample = 1;
  cfg.ring = 64;
  cfg.bytes = 16;
  cap::Ring ring(cfg);
  assert(ring.ring_size() == 64 && ring.cap_bytes() == 16);
  // 200 frames of 24 bytes each: every slot overwritten 3+ times,
  // every stored payload truncated to cap_bytes
  for (int i = 0; i < 200; ++i) {
    uint8_t p[24];
    for (int k = 0; k < 24; ++k) p[k] = uint8_t(i ^ (k * 7));
    assert(ring.Sampled());
    ring.Record(1000 + i, uint64_t(100 + i), p, sizeof(p));
  }
  assert(ring.recorded() == 200);
  std::vector<cap::CapRecord> snap;
  ring.Snapshot(&snap, 1000);
  assert(snap.size() == 64);
  // newest-first: snap[j] is frame 199 - j, byte-for-byte
  for (size_t j = 0; j < snap.size(); ++j) {
    const int i = 199 - int(j);
    assert(snap[j].ts_us == 1000 + i);
    assert(snap[j].conn == uint64_t(100 + i));
    assert(snap[j].frame_len == 24);        // true wire length kept
    assert(snap[j].payload.size() == 16);   // stored prefix truncated
    assert(snap[j].ver == uint8_t(i ^ 0));
    assert(snap[j].tag == uint8_t(i ^ 7));
    for (int k = 0; k < 16; ++k)
      assert(snap[j].payload[size_t(k)] == uint8_t(i ^ (k * 7)));
  }
  // bounded snapshot takes the newest max_n only
  ring.Snapshot(&snap, 5);
  assert(snap.size() == 5 && snap[0].ts_us == 1199);
}

void test_ring_sampling_and_set() {
  cap::Config cfg;
  cfg.sample = 5;
  cfg.ring = 64;
  cfg.bytes = 32;
  cap::Ring ring(cfg);
  int recorded = 0;
  for (int i = 0; i < 100; ++i) {
    if (ring.Sampled()) {
      const uint8_t p[2] = {1, 2};
      ring.Record(i, 1, p, 2);
      ++recorded;
    }
  }
  assert(recorded == 20);  // 1-in-5 dice, single thread: exact
  assert(ring.recorded() == 20);
  ring.Set(0);             // runtime off: the one-relaxed-load path
  for (int i = 0; i < 100; ++i) assert(!ring.Sampled());
  ring.Set(1);             // back on: every frame
  assert(ring.Sampled());
  ring.Set(-1);            // negative keeps current
  assert(ring.sample() == 1);
}

void test_save_file_round_trip() {
  cap::Config cfg;
  cfg.sample = 1;
  cfg.ring = 64;
  cfg.bytes = 64;
  cap::Ring ring(cfg);
  for (int i = 0; i < 10; ++i) {
    uint8_t p[6] = {uint8_t(1 + (i & 1)), uint8_t(0x60 + i), 'x', 'y',
                    uint8_t(i), 0};
    ring.Record(5000 + i, uint64_t(i), p, sizeof(p));
  }
  const char *path = "/tmp/ptpu_drill_selftest.cap";
  assert(ring.SaveFile(path) == 10);
  FILE *f = std::fopen(path, "rb");
  assert(f);
  std::vector<uint8_t> bytes(1 << 16);
  bytes.resize(std::fread(bytes.data(), 1, bytes.size(), f));
  std::fclose(f);
  std::remove(path);
  std::vector<cap::CapRecord> out;
  assert(cap::ParseCaptureBytes(bytes.data(), bytes.size(), &out) ==
         cap::ParseResult::kOk);
  assert(out.size() == 10);
  // files are oldest-first (replay order), unlike snapshots
  for (int i = 0; i < 10; ++i) {
    assert(out[size_t(i)].ts_us == 5000 + i);
    assert(out[size_t(i)].tag == uint8_t(0x60 + i));
    assert(out[size_t(i)].payload.size() == 6);
  }
}

void test_capturez_route_and_runtime_set() {
  cap::Ring &g = cap::Global();
  g.Set(1);
  Options opt = base_opts("drill-key");
  opt.http_port = 0;
  EchoServer es(opt);
  const int fd = dial(es.srv->port());
  assert(client_handshake(fd, "drill-key"));
  const uint64_t before = g.recorded();
  std::vector<uint8_t> rep;
  for (uint8_t i = 0; i < 3; ++i) {
    send_frame(fd, {0x01, uint8_t(0x60 + i), 'd', i});
    assert(recv_frame(fd, &rep) && rep.size() == 4);
  }
  assert(g.recorded() == before + 3);

  const std::string http = http_get(es.srv->http_port(),
                                    "/capturez?n=2");
  assert(http.find("HTTP/1.1 200") == 0);
  assert(http.find("application/json") != std::string::npos);
  assert(http.find("\"frames\":[") != std::string::npos);
  // newest-first: frames[0] is the LAST echo frame, full hex payload
  assert(http.find("\"data\":\"016264") != std::string::npos);
  assert(http.find("\"tag\":98") != std::string::npos);  // 0x62
  // n=2 honored: exactly two frame objects in the window
  size_t n_frames = 0;
  for (size_t at = 0; (at = http.find("\"ts_us\":", at)) !=
                      std::string::npos;
       ++at)
    ++n_frames;
  assert(n_frames == 2);

  // runtime off: traffic flows, nothing new is recorded
  g.Set(0);
  const uint64_t frozen = g.recorded();
  send_frame(fd, {0x01, 0x60, 'z'});
  assert(recv_frame(fd, &rep) && rep.size() == 3);
  assert(g.recorded() == frozen);
  ::close(fd);
}

void test_chaos_kill_reconciles_exactly() {
  Options opt = base_opts("kill-key");
  opt.chaos.kill = true;
  opt.chaos.rate = 3;
  EchoServer es(opt);
  // single event thread + kill-only chaos: the dice is consumed once
  // per post-handshake frame, so deaths land deterministically and
  // every injected kill maps 1:1 to a client-observed EOF
  int client_deaths = 0;
  int echoed = 0;
  while (client_deaths < 3) {
    const int fd = dial(es.srv->port());
    assert(client_handshake(fd, "kill-key"));
    std::vector<uint8_t> rep;
    for (;;) {
      send_frame(fd, {0x01, 0x60, 'k'});
      if (!recv_frame(fd, &rep)) {
        ++client_deaths;
        break;
      }
      ++echoed;
    }
    ::close(fd);
  }
  assert(es.stats.chaos_conn_kills.Get() == 3);
  assert(echoed == 4);  // dice hits on frames 1, 4, 7 — 2+2 echo between
  assert(es.stats.handshake_fails.Get() == 0);
}

void test_chaos_hsdrop_counted_as_handshake_fail() {
  Options opt = base_opts("hs-key");
  opt.chaos.hsdrop = true;
  opt.chaos.rate = 1;  // every valid MAC dropped
  EchoServer es(opt);
  for (int i = 0; i < 3; ++i) {
    const int fd = dial(es.srv->port());
    assert(!client_handshake(fd, "hs-key"));
    ::close(fd);
  }
  assert(es.stats.chaos_handshake_drops.Get() == 3);
  assert(es.stats.handshake_fails.Get() == 3);
  // drills must not mask REAL auth failures: a wrong key is a
  // handshake_fail but never a chaos drop
  const int fd = dial(es.srv->port());
  assert(!client_handshake(fd, "wrong"));
  ::close(fd);
  assert(es.stats.chaos_handshake_drops.Get() == 3);
  assert(es.stats.handshake_fails.Get() == 4);
}

void test_chaos_delays_and_short_writes_lossless() {
  Options opt = base_opts("slow-key");
  opt.chaos.rdelay = true;
  opt.chaos.wdelay = true;
  opt.chaos.shortw = true;
  opt.chaos.rate = 1;
  opt.chaos.delay_us = 500;
  EchoServer es(opt);
  const int fd = dial(es.srv->port());
  assert(client_handshake(fd, "slow-key"));
  // a 100-byte echo through 1-byte chaos writes: the remainder rides
  // the partial-write EPOLLOUT path and arrives INTACT — delay-style
  // chaos loses nothing, it only stretches time
  std::vector<uint8_t> big(100);
  for (size_t i = 0; i < big.size(); ++i) big[i] = uint8_t(i * 3);
  std::vector<uint8_t> rep;
  for (int round = 0; round < 3; ++round) {
    send_frame(fd, big);
    assert(recv_frame(fd, &rep));
    assert(rep == big);
  }
  ::close(fd);
  assert(es.stats.chaos_read_delays.Get() > 0);
  assert(es.stats.chaos_write_delays.Get() > 0);
  assert(es.stats.chaos_short_writes.Get() >= 100);
  assert(es.stats.partial_write_flushes.Get() > 0);
}

void test_chaos_env_parse() {
  // OptionsFromEnv twin checks: kinds list + rate, unknown kinds and
  // bad rates leave chaos OFF (fault injection must never turn on by
  // accident)
  auto parse = [](const char *v) {
    ::setenv("PTPU_CHAOS", v, 1);
    Options o = ptpu::net::OptionsFromEnv(Options());
    ::unsetenv("PTPU_CHAOS");
    return o.chaos;
  };
  auto c = parse("kill,rdelay:100");
  assert(c.kill && c.rdelay && !c.wdelay && !c.shortw && !c.hsdrop);
  assert(c.rate == 100 && c.enabled());
  c = parse("all:7");
  assert(c.kill && c.rdelay && c.wdelay && c.shortw && c.hsdrop &&
         c.rate == 7);
  assert(!parse("kill").enabled());        // no rate
  assert(!parse("kill:0").enabled());      // zero rate
  assert(!parse("kill:-5").enabled());     // negative rate
  assert(!parse("kill:12x").enabled());    // trailing junk
  assert(!parse("nuke:5").enabled());      // unknown kind
  assert(!parse(":5").enabled());          // empty kinds
  assert(!parse("").enabled());
  ::setenv("PTPU_CHAOS_DELAY_US", "250", 1);
  ::setenv("PTPU_CHAOS", "wdelay:9", 1);
  Options o = ptpu::net::OptionsFromEnv(Options());
  ::unsetenv("PTPU_CHAOS");
  ::unsetenv("PTPU_CHAOS_DELAY_US");
  assert(o.chaos.wdelay && o.chaos.delay_us == 250);
}

}  // namespace

// announce each test on stderr (unbuffered) BEFORE it runs — a hang
// names its test instead of leaving a silent stuck binary
#define RUN(t)                       \
  do {                               \
    std::fprintf(stderr, "  %s\n", #t); \
    t();                             \
  } while (0)

int main() {
  // the global ring reads its env config at FIRST touch — pin it
  // before any traffic so the /capturez test sees a known shape
  ::setenv("PTPU_CAPTURE_RING", "64", 1);
  ::setenv("PTPU_CAPTURE_BYTES", "64", 1);
  RUN(test_capture_parse_reject_family);
  RUN(test_ring_wraparound_exact);
  RUN(test_ring_sampling_and_set);
  RUN(test_save_file_round_trip);
  RUN(test_capturez_route_and_runtime_set);
  RUN(test_chaos_kill_reconciles_exactly);
  RUN(test_chaos_hsdrop_counted_as_handshake_fail);
  RUN(test_chaos_delays_and_short_writes_lossless);
  RUN(test_chaos_env_parse);
  std::printf("ptpu_drill_selftest: all native drill-harness unit "
              "tests passed\n");
  return 0;
}
