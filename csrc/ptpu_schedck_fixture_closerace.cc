// Seeded historical-bug fixture: the r9 listen-fd close-before-join
// race.
//
// The original r9 server shutdown closed the listening fd BEFORE
// stopping and joining the acceptor thread. An acceptor woken by a
// late connection then called accept4() on a closed — and possibly
// already reused — descriptor: EBADF on a good day, accepting on a
// stranger's fd on a bad one. The fix (r9, kept ever since in
// ptpu_net.cc Server::Stop) is stop-then-join-THEN-close. This
// fixture reintroduces the buggy ordering as a model (BlockUntil =
// epoll_wait on the listen fd; SCHEDCK_ASSERT(fd_open) = the
// accept4() call) and asserts that ptpu_schedck
//   1. rediscovers the use-after-close within a bounded schedule
//      budget, under BOTH strategies (dfs exhaustively, pct
//      probabilistically),
//   2. replays it from the recorded decision trace on the FIRST
//      schedule, with a byte-identical report, and
//   3. passes the FIXED stop-join-close ordering exhaustively clean
//      (the negative control — mirroring the lockdep fixture
//      pattern).
//
// Built only by the schedck targets (-DPTPU_SCHEDCK -DPTPU_LOCKDEP);
// runs in `make selftest`, both sancheck legs and the run_checks
// schedck leg.
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

#include <atomic>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>

#include "ptpu_schedck.h"
#include "ptpu_sync.h"

namespace sck = ptpu::schedck;

namespace {

constexpr uint64_t kBudget = 5000;  // discovery budget, both legs
const char* kTracePath = "ptpu_schedck_fixture_closerace.trace";

int g_tests = 0;

void ok(const char* name) {
  ++g_tests;
  std::printf("ok %2d - %s\n", g_tests, name);
  std::fflush(stdout);
}

void fail(const char* why, const std::string& detail) {
  std::fprintf(stderr, "FAIL closerace fixture: %s\n%s\n", why,
               detail.c_str());
  std::exit(1);
}

// The acceptor/shutdown model. `close_before_join` selects the
// seeded r9 buggy (true) or the FIXED (false) teardown ordering.
void ServerRound(bool close_before_join) {
  struct St {
    std::atomic<bool> stop{false};
    std::atomic<bool> fd_open{true};
    std::atomic<int> pending{0};
    int accepted = 0;
  } st;
  sck::Thread acceptor([&st] {
    for (;;) {
      // epoll_wait on the listen fd (a stop request also wakes it)
      sck::BlockUntil(
          [&st] {
            return st.stop.load() || st.pending.load() > 0;
          },
          "epoll_wait(listen fd)");
      if (st.pending.load() > 0) {
        // accept4(listen_fd, ...): the fd must still be ours
        SCHEDCK_ASSERT(st.fd_open.load());
        st.pending.fetch_sub(1);
        ++st.accepted;
        PTPU_SCHED_POINT();  // hand the conn off, poll again
        continue;
      }
      if (st.stop.load()) break;
    }
  });
  sck::Thread client([&st] {
    PTPU_SCHED_POINT();  // connect() lands at an arbitrary time
    st.pending.fetch_add(1);
  });
  if (close_before_join) {
    // r9 bug: close the listen fd while the acceptor still runs
    st.fd_open.store(false);
    PTPU_SCHED_POINT();  // a late connect wakes the acceptor here
    st.stop.store(true);
    acceptor.join();
  } else {
    // the r9 fix: stop, join, and only then close the fd
    st.stop.store(true);
    acceptor.join();
    st.fd_open.store(false);
  }
  client.join();
}

void BuggyBody() { ServerRound(true); }
void FixedBody() { ServerRound(false); }

void ChildDiscoverDfs() {
  sck::Options o;
  o.strategy = sck::Options::Strategy::kDfs;
  o.max_schedules = kBudget;
  o.depth = 10;
  o.trace_out = kTracePath;
  sck::Explore("closerace_buggy", BuggyBody, o);
}

void ChildDiscoverPct() {
  sck::Options o;
  o.strategy = sck::Options::Strategy::kPct;
  o.max_schedules = kBudget;
  o.depth = 3;
  o.seed = 1;
  o.trace_out = kTracePath;
  sck::Explore("closerace_buggy", BuggyBody, o);
}

void ChildReplay() {
  sck::Replay("closerace_buggy", BuggyBody, kTracePath);
}

// Fork `fn`; expect SIGABRT; return the child's stderr.
std::string RunDeathTest(void (*fn)()) {
  int fds[2];
  if (pipe(fds) != 0) fail("pipe failed", "");
  const pid_t pid = fork();
  if (pid < 0) fail("fork failed", "");
  if (pid == 0) {
    close(fds[0]);
    dup2(fds[1], 2);
    close(fds[1]);
    fn();
    _exit(0);  // no failure found == fixture bug not rediscovered
  }
  close(fds[1]);
  std::string err;
  char buf[4096];
  ssize_t n;
  while ((n = read(fds[0], buf, sizeof(buf))) > 0)
    err.append(buf, size_t(n));
  close(fds[0]);
  int wst = 0;
  waitpid(pid, &wst, 0);
  if (!WIFSIGNALED(wst) || WTERMSIG(wst) != SIGABRT)
    fail("expected SIGABRT (bug not rediscovered in budget)", err);
  return err;
}

uint64_t ParseSchedule(const std::string& report) {
  const size_t p = report.find("schedule ");
  if (p == std::string::npos) fail("no schedule in report", report);
  return std::strtoull(report.c_str() + p + 9, nullptr, 10);
}

void CheckDiscovery(void (*child)(), const char* what) {
  std::remove(kTracePath);
  const std::string rep = RunDeathTest(child);
  if (rep.find("ASSERTION FAILED") == std::string::npos)
    fail("expected an ASSERTION FAILED report", rep);
  if (rep.find("fd_open") == std::string::npos)
    fail("assertion is not the accept-after-close one", rep);
  FILE* f = std::fopen(kTracePath, "r");
  if (!f) fail("no decision trace written", rep);
  std::fclose(f);
  const uint64_t k = ParseSchedule(rep);
  if (k >= kBudget) fail("discovery outside budget", rep);
  std::printf("ok %2d - %s rediscovered the r9 close-before-join "
              "race at schedule %llu (budget %llu)\n",
              ++g_tests, what, (unsigned long long)k,
              (unsigned long long)kBudget);
  std::fflush(stdout);
}

}  // namespace

int main() {
  std::printf("ptpu_schedck_fixture_closerace: r9 listen-fd "
              "close-before-join race\n");
  CheckDiscovery(ChildDiscoverDfs, "dfs");
  // replay the DFS-found trace: identical failure, first schedule, 3x
  std::string prev;
  for (int i = 0; i < 3; ++i) {
    const std::string r = RunDeathTest(ChildReplay);
    if (r.find("strategy replay  schedule 0") == std::string::npos)
      fail("replay did not reproduce on the first schedule", r);
    if (r.find("ASSERTION FAILED") == std::string::npos)
      fail("replay reproduced a different failure", r);
    if (i > 0 && r != prev)
      fail("replay reports differ across runs", r);
    prev = r;
  }
  ok("trace replays the identical assertion, 3x, on schedule 0");
  CheckDiscovery(ChildDiscoverPct, "pct");
  std::remove(kTracePath);
  // negative control: the FIXED teardown is exhaustively clean
  {
    sck::Options o;
    o.strategy = sck::Options::Strategy::kDfs;
    o.max_schedules = 200000;
    o.depth = 10;
    const sck::Result r =
        sck::Explore("closerace_fixed", FixedBody, o);
    if (!r.exhausted)
      fail("clean control did not exhaust the space", "");
    std::printf("ok %2d - fixed stop-join-close teardown clean "
                "(%llu schedules, exhaustive)\n",
                ++g_tests, (unsigned long long)r.schedules);
  }
  std::remove("closerace_buggy.schedck-trace");  // replay re-records
  std::printf("all closerace fixture checks passed (%d tests)\n",
              g_tests);
  return 0;
}
