// C ABI for the native parameter-server shard table
// (csrc/ptpu_ps_table.cc — the C-hosted PS hot path).
//
// Reference counterpart: the brpc PS service's table storage
// (distributed/ps/table/common_dense_table.cc /
// common_sparse_table.cc — MemorySparseTable row storage with the
// optimizer applied server-side inside the table). Here the shard's
// rows plus per-row optimizer slots live in ONE contiguous allocation
// laid out by the shared ptpu::PlanArena (csrc/ptpu_arena.h), and the
// hot ops are bounds-checked gather (pull) and duplicate-coalescing
// scatter-update (push).
//
// Consumed via ctypes (paddle_tpu/core/native.py NativePsTable); the
// numpy `_Shard` in distributed/ps/table.py remains the byte-parity
// fallback when this library is absent.
#ifndef PTPU_PS_TABLE_H_
#define PTPU_PS_TABLE_H_

#include <stdint.h>

#if defined(_WIN32)
#define PTPU_PS_EXPORT extern "C" __declspec(dllexport)
#else
#define PTPU_PS_EXPORT extern "C" __attribute__((visibility("default")))
#endif

// Server-side optimizers applied by push (reference: the accessor /
// sparse-optimizer kinds in table/sparse_sgd_rule.cc).
enum PtpuPsOptimizer {
  PTPU_PS_SGD = 0,      // w -= lr * g
  PTPU_PS_ADAGRAD = 1,  // g2 += g*g; w -= lr * g / (sqrt(g2) + eps)
  PTPU_PS_ADAM = 2,     // per-row step count; bias-corrected m/v
};

PTPU_PS_EXPORT const char *ptpu_ps_last_error(void);
PTPU_PS_EXPORT const char *ptpu_ps_version(void);

// Create a shard of `rows` x `dim` float32 weights (plus optimizer
// slots as the kind requires). Returns NULL on error.
PTPU_PS_EXPORT void *ptpu_ps_table_create(int64_t rows, int64_t dim,
                                          int optimizer, float lr,
                                          float beta1, float beta2,
                                          float eps);
PTPU_PS_EXPORT void ptpu_ps_table_destroy(void *h);

// Direct pointer to the row-major weight block — the binding wraps it
// as a numpy view for seeded init and parity inspection. The caller
// must not hold the view across destroy.
PTPU_PS_EXPORT float *ptpu_ps_table_data(void *h);
PTPU_PS_EXPORT int64_t ptpu_ps_table_rows(void *h);
PTPU_PS_EXPORT int64_t ptpu_ps_table_dim(void *h);
// Total bytes of the one arena allocation (weights + slots).
PTPU_PS_EXPORT uint64_t ptpu_ps_table_bytes(void *h);

// Gather rows[ids[i]] into out (n x dim, row-major). Local ids.
// Concurrent pulls run in parallel (shared lock). Returns 0, or -1
// with ptpu_ps_last_error set (out-of-range id).
PTPU_PS_EXPORT int ptpu_ps_table_pull(void *h, const int64_t *ids,
                                      int64_t n, float *out);

// Scatter-update: duplicate ids accumulate their grads first, then the
// optimizer updates each unique row once (exclusive lock). Returns 0,
// or -1 with ptpu_ps_last_error set (out-of-range id).
PTPU_PS_EXPORT int ptpu_ps_table_push(void *h, const int64_t *ids,
                                      int64_t n, const float *grads);

// Same update, but `grads` is an UNALIGNED byte buffer of n*dim LE f32
// values — the data-plane server passes a view straight into the
// received frame (whose float block lands at whatever offset the
// table-name length left it); values are read with per-element memcpy
// so no aligned staging copy is ever made.
PTPU_PS_EXPORT int ptpu_ps_table_push_raw(void *h, const int64_t *ids,
                                          int64_t n, const void *grads);

// Reader-lock bracket for callers that stream rows out WITHOUT a
// gather copy (the data-plane server writev's row pointers straight
// into the socket): rows are stable between rdlock and unlock;
// concurrent pulls proceed, pushes wait.
PTPU_PS_EXPORT void ptpu_ps_table_rdlock(void *h);
PTPU_PS_EXPORT void ptpu_ps_table_rdunlock(void *h);

// ---- observability (csrc/ptpu_stats.h core) -------------------------
// Storage-level counters, always-on relaxed atomics: pull_ops /
// pull_rows / push_ops / push_rows / push_coalesced_rows (duplicate
// ids merged before the optimizer ran). The numpy fallback shard
// (distributed/ps/table.py) maintains the same names so native and
// fallback snapshots are comparable.

// JSON snapshot of the table's counters. The returned pointer is a
// thread-local render buffer, valid until the calling thread's next
// ptpu_ps_table_stats_json call.
PTPU_PS_EXPORT const char *ptpu_ps_table_stats_json(void *h);
PTPU_PS_EXPORT void ptpu_ps_table_stats_reset(void *h);
// Credit a pull served by an external gather (the data-plane server
// copies rows under rdlock without calling ptpu_ps_table_pull).
PTPU_PS_EXPORT void ptpu_ps_table_note_pull(void *h, int64_t nrows);

#endif  // PTPU_PS_TABLE_H_
