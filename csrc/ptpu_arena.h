// Best-fit free-list machinery shared by the runtime host allocator
// (csrc/ptpu_runtime.cc BestFitArena — real memory, grown in malloc'd
// chunks) and the native predictor's static memory planner
// (csrc/ptpu_predictor.cc plan_memory — a *virtual* offset space whose
// final size becomes the one serving arena). Both need the same core:
// free blocks kept in a size-ordered multimap for best-fit lookup and an
// address-ordered map for adjacency coalescing.
//
// Reference counterpart: the free-list bookkeeping inside
// memory/allocation/auto_growth_best_fit_allocator.cc and the inference
// memory-optimize pass (inference/analysis/passes/memory_optimize_pass.cc)
// which plans tensor offsets from lifetimes the same way.
#ifndef PTPU_ARENA_H_
#define PTPU_ARENA_H_

#include <cstddef>
#include <cstdint>
#include <map>

namespace ptpu {

// P is a pointer-like address type: char* for the runtime allocator,
// uint64_t byte offsets for the planner. Requires +, comparison.
template <class P>
class BestFitFreeList {
 public:
  // Insert block [p, p+n), coalescing with free neighbors.
  void Add(P p, size_t n) {
    auto next = by_addr_.find(p + n);
    if (next != by_addr_.end()) {
      size_t nn = next->second;
      Erase(p + n, nn);
      n += nn;
    }
    auto prev = by_addr_.lower_bound(p);
    if (prev != by_addr_.begin()) {
      --prev;
      if (prev->first + prev->second == p) {
        P pp = prev->first;
        size_t pn = prev->second;
        Erase(pp, pn);
        p = pp;
        n += pn;
      }
    }
    by_addr_[p] = n;
    by_size_.emplace(n, p);
  }

  // Best-fit: smallest free block of size >= n. Removes the block and
  // returns its base and full size (caller re-Adds any remainder).
  bool Take(size_t n, P* out_p, size_t* out_n) {
    auto it = by_size_.lower_bound(n);
    if (it == by_size_.end()) return false;
    *out_p = it->second;
    *out_n = it->first;
    Erase(*out_p, *out_n);
    return true;
  }

  void Erase(P p, size_t n) {
    by_addr_.erase(p);
    auto range = by_size_.equal_range(n);
    for (auto i = range.first; i != range.second; ++i) {
      if (i->second == p) {
        by_size_.erase(i);
        break;
      }
    }
  }

  bool Empty() const { return by_addr_.empty(); }

  // size of the free block ending exactly at `end`, 0 if none — lets
  // a growing arena extend a partially-free tail instead of appending
  // a full new block after it
  size_t TailAt(P end) const {
    if (by_addr_.empty()) return 0;
    auto it = by_addr_.lower_bound(end);
    if (it == by_addr_.begin()) return 0;
    --it;
    return it->first + it->second == end ? it->second : 0;
  }

 private:
  std::map<P, size_t> by_addr_;
  std::multimap<size_t, P> by_size_;
};

// Offset-space arena for static memory planning: Alloc/Free operate on
// byte offsets during the load-time lifetime walk; Size() afterwards is
// the peak footprint — the single allocation the executor makes.
class PlanArena {
 public:
  explicit PlanArena(size_t align = 64) : align_(align) {}

  uint64_t Alloc(size_t n) {
    n = RoundUp(n ? n : 1);
    uint64_t p = 0;
    size_t block = 0;
    if (!free_.Take(n, &p, &block)) {
      // grow the virtual space by only the UNCOVERED portion: a free
      // tail block is extended (Add coalesces), keeping Size() at the
      // true peak footprint
      const size_t tail = free_.TailAt(size_);  // < n, else Take hit
      free_.Add(size_, n - tail);
      size_ += n - tail;
      free_.Take(n, &p, &block);
    }
    if (block > n) free_.Add(p + n, block - n);
    return p;
  }

  void Free(uint64_t off, size_t n) { free_.Add(off, RoundUp(n ? n : 1)); }

  uint64_t Size() const { return size_; }

 private:
  size_t RoundUp(size_t n) const { return (n + align_ - 1) / align_ * align_; }

  BestFitFreeList<uint64_t> free_;
  uint64_t size_ = 0;
  size_t align_;
};

}  // namespace ptpu

#endif  // PTPU_ARENA_H_
