// C-hosted concurrent inference serving runtime — the wire + batching
// half of native serving (csrc/ptpu_predictor.cc holds the execution
// half, reached ONLY through its public C ABI in
// csrc/ptpu_inference_api.h so the layering stays testable).
//
// Reference counterpart: the multi-threaded serving stack over
// AnalysisPredictor — `paddle_infer::services::PredictorPool` fanned
// out behind a request server, plus the dynamic batching every
// serving system grows (Clipper NSDI'17; batching queues in Orca
// OSDI'22). Three pieces:
//
//   * Parallel instances: N serving instances, each owning a PRIVATE
//     WorkPool sub-pool (ptpu_workpool_create) attached to all of its
//     predictors, so concurrent batches execute truly in parallel
//     instead of serializing on the global dispatch mutex.
//   * Dynamic micro-batcher: a lock+condvar FIFO of requests that
//     flushes when `max_batch` rows accumulate or `deadline_us` has
//     passed since the oldest queued request; requests are stitched
//     into one batched run and de-muxed row-wise, strictly FIFO.
//   * Bucket ladder: at load time the artifact is re-planned for
//     batch sizes {1,2,4,...,max_batch} (ptpu_predictor_create_opts
//     batch_override), so every batched run binds into a pre-planned
//     arena — zero per-run allocation. A flush whose row count has no
//     exact bucket pads up to the next one (counted in bucket_miss);
//     runs that still fall off a planned arena surface in
//     dynamic_shape_fallback.
//
// Wire protocol (mirrors the PS data plane, csrc/ptpu_ps_server.cc):
//   * connect: 16-byte nonce -> HMAC-SHA256(authkey, nonce) frame ->
//     one byte 0x01 (csrc/ptpu_hmac.h).
//   * frames: u32-LE length prefix + payload both ways; payload leads
//     with [u8 version][u8 tag].
//       0x60 INFER_REQ  [u64 req_id][u16 n_inputs] then per input
//                       [u8 onnx_dtype][u8 ndim][ndim x i64 dims][raw]
//       0x61 INFER_REP  [u64 req_id][u16 n_outputs] then per output
//                       [u8 ndim][ndim x i64 dims][f32 raw]
//       0x62 INFER_ERR  [u64 req_id][u32 len][msg]
//       0x63 META_REQ   (empty) -> 0x64 META_REP [u32 len][json]
//   req_id is caller-chosen; replies may interleave across a
//   connection's in-flight requests (client pipelining).
//
// Connection handling rides the shared epoll core
// (csrc/ptpu_net.{h,cc}): INFER frames parse on the event threads and
// enqueue into the micro-batcher; batch completions on the instance
// workers queue replies on the connection and wake its owner event
// loop over an eventfd — workers never block on a client socket. A
// full request queue DEFERS the frame (reads from that connection
// pause; the event loop re-dispatches on a timer) instead of sleeping
// an event thread, bounding backpressure without blocking.
//
// Build: linked with ptpu_predictor.cc + ptpu_net.cc into
// paddle_tpu/_native_predictor.so (csrc/Makefile); unit-tested by
// csrc/ptpu_serving_selftest.cc.

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstdio>
#include <cstring>
#include <deque>
#include <functional>
#include <map>
#include <memory>
#include <set>
#include <string>
#include <thread>
#include <vector>

#include "ptpu_inference_api.h"
#include "ptpu_invar.h"
#include "ptpu_net.h"
#include "ptpu_schedck.h"
#include "ptpu_stats.h"
#include "ptpu_sync.h"
#include "ptpu_topo.h"
#include "ptpu_trace.h"
#include "ptpu_tune.h"
#include "ptpu_wire.h"

namespace {

// Lock classes of the serving runtime (rank table: README
// "Correctness tooling"). kv is held across whole decode runs (the
// predictor blocks on its WorkPool inside) -> kLockAllowBlock; the
// registry lock nests inside it, and reply sends (net.conn_out, rank
// 100) nest inside both.
PTPU_LOCK_CLASS(kLockSvKv, "sv.kv", 10, ptpu::kLockAllowBlock);
// shadow-mirror predictors are shared across instance workers and the
// predictor is thread-compatible, not thread-safe: the shadow run
// serializes on this lock (held across a blocking run, like sv.kv;
// ranked under sv.sess so it can never invert with the registry)
PTPU_LOCK_CLASS(kLockSvShadow, "sv.shadow", 15, ptpu::kLockAllowBlock);
PTPU_LOCK_CLASS(kLockSvSess, "sv.sess", 20);
PTPU_LOCK_CLASS(kLockSvBatcher, "sv.batcher", 30);

constexpr uint8_t kSvWireVersion = 1;
// Traced frames (ISSUE 10): [ver=2][tag][u64 trace id] then the v1
// body; REP frames for a traced request echo the same extension (ERR
// frames stay v1). Old v1 clients are untouched. Python twin:
// inference/serving.py WIRE_VERSION_TRACED.
constexpr uint8_t kSvWireVersionTraced = 2;
constexpr uint8_t kTagInferReq = 0x60;
constexpr uint8_t kTagInferRep = 0x61;
constexpr uint8_t kTagInferErr = 0x62;
constexpr uint8_t kTagMetaReq = 0x63;
constexpr uint8_t kTagMetaRep = 0x64;
/* KV-cached decode ops (ISSUE r9): sessions are server-side KV slots
 * in the decode predictor; a step feeds one token into one session
 * and answers that session's next-token logits. Layouts (payload
 * offsets, after the u32 frame length):
 *   DECODE_OPEN  [ver][tag][u64 req_id]                      (10 B)
 *   DECODE_SESS  [ver][tag][u64 req_id][u64 session]         (18 B)
 *   DECODE_STEP  [ver][tag][u64 req_id][u64 session][i64 tok](26 B)
 *   DECODE_REP   [ver][tag][u64 req_id][u64 session]
 *                [u32 n_logits][f32 x n]
 *   DECODE_CLOSE [ver][tag][u64 req_id][u64 session] -> SESS echo
 * Errors ride the existing INFER_ERR frame. Python twin:
 * inference/serving.py TAG_DECODE_* (tools/ptpu_check.py wire checker
 * holds the two in lockstep). */
constexpr uint8_t kTagDecodeOpen = 0x65;
constexpr uint8_t kTagDecodeSess = 0x66;
constexpr uint8_t kTagDecodeStep = 0x67;
constexpr uint8_t kTagDecodeRep = 0x68;
constexpr uint8_t kTagDecodeClose = 0x69;
/* Paged-engine ops (ISSUE r12). OPEN2 opens a session WITH its prompt:
 * the server adopts shared prefix pages from the prompt cache, then
 * prefills the rest in bounded chunks interleaved with running decode
 * steps through the same micro-batcher (a long prompt never stalls
 * running sessions), answering once with the last prompt token's
 * logits. FORK clones a live session copy-on-write (parallel sampling
 * from one prefix) and echoes the NEW session id as DECODE_SESS.
 *   DECODE_OPEN2    [ver][tag][u64 req_id][u32 n_tokens][u32 flags=0]
 *                   [n_tokens x i64 tokens]       (18 + 8n B)
 *   DECODE_OPEN_REP [ver][tag][u64 req_id][u64 session]
 *                   [u32 adopted_tokens][u32 n_logits][n x f32]
 *   DECODE_FORK     [ver][tag][u64 req_id][u64 session] -> SESS echo
 * Tag bytes/layouts mirror inference/serving.py TAG_DECODE_*
 * (tools/ptpu_check.py wire checker enforces both). */
constexpr uint8_t kTagDecodeOpen2 = 0x6a;
constexpr uint8_t kTagDecodeOpenRep = 0x6b;
constexpr uint8_t kTagDecodeFork = 0x6c;
/* Speculative-decoding ops (ISSUE 13). A spec session runs a DRAFT
 * model alongside the target: each SPEC_STEP is one draft/verify
 * round — the draft proposes k tokens (k sequential width-1 draft
 * steps), the target scores all k plus the bonus position in ONE
 * width-(k+1) pass through the spec_verify artifact, the standard
 * exact acceptance rule (greedy: longest matching prefix; sampling:
 * modified rejection against the draft distribution) commits m
 * accepted tokens + 1 target-sourced token, and the rejected suffix
 * rolls back by TRUNCATING the session's paged block table (kv_trim —
 * COW pages are unreferenced, never mutated). Zero distribution
 * drift by construction.
 *   DECODE_SPEC_OPEN [ver][tag][u64 req_id][u32 n_tokens]
 *                    [u32 flags][u64 seed][n x i64]  (26 + 8n B)
 *                    flags bit0: 1 = sampling, 0 = greedy; seed
 *                    drives the server-side sampler (splitmix64).
 *   DECODE_SPEC_STEP [ver][tag][u64 req_id][u64 session]  (18 B)
 *   DECODE_SPEC_REP  [ver][tag][u64 req_id][u64 session]
 *                    [u32 accepted][u32 n_tokens][n x i64]
 *                    open: accepted = prefix-cache adopted tokens and
 *                    n = 1 (the first generated token); step:
 *                    accepted = draft tokens accepted this round and
 *                    n = accepted + 1 (clients see tokens-per-round).
 * Errors ride INFER_ERR. Python twin: inference/serving.py
 * TAG_DECODE_SPEC_* (the wire checker holds the two in lockstep). */
constexpr uint8_t kTagDecodeSpecOpen = 0x6d;
constexpr uint8_t kTagDecodeSpecStep = 0x6e;
constexpr uint8_t kTagDecodeSpecRep = 0x6f;
constexpr uint32_t kSvMaxFrame = 1u << 30;
constexpr int kSvMaxNdim = 16;
// backpressure budget: how long one INFER frame may sit deferred on a
// full queue before it answers an error (matches the old 200 x 500us
// blocking-retry budget)
constexpr int64_t kSvDeferBudgetUs = 100 * 1000;

// ONNX TensorProto dtype codes accepted on the wire
enum { SV_F32 = 1, SV_I32 = 6, SV_I64 = 7 };

inline int sv_dtype_size(int dt) {
  return dt == SV_I64 ? 8 : dt == SV_I32 || dt == SV_F32 ? 4 : 0;
}

using ptpu::GetU32;
using ptpu::PutU32;

struct SvInput {
  int dtype = SV_F32;
  std::vector<int64_t> dims;
  std::vector<uint8_t> data;
  /* Zero-copy ingestion (ISSUE 17a): when the owning SvRequest pinned
   * the conn's reassembly buffer, `ext` views the payload bytes in
   * place and `data` stays empty; the batch gather reads straight
   * from the wire bytes. Detached conns (fuzz harnesses pumping
   * caller-owned memory) cannot be pinned — they fall back to the
   * copying `data` path. */
  const uint8_t* ext = nullptr;
  size_t ext_n = 0;
  const uint8_t* bytes() const { return ext ? ext : data.data(); }
  size_t nbytes() const { return ext ? ext_n : data.size(); }
};

struct SvRequest {
  uint64_t id = 0;
  int64_t rows = 0;
  std::vector<SvInput> inputs;
  // holds the conn's reassembly buffer alive while inputs[i].ext
  // views point into it (released with the request, after the batch
  // gather consumed the bytes)
  std::shared_ptr<const void> pin;
  ptpu::net::ConnPtr conn;
  int64_t t_enq_us = 0;
  // decode steps ride the same batcher machinery as INFER requests
  // (continuous batching of decode steps across sessions)
  bool is_decode = false;
  // server-internal prompt-prefill step (ISSUE r12 chunked prefill):
  // no per-step reply; completion is tracked on the session's
  // PrefillJob, which answers DECODE_OPEN_REP after the LAST token
  bool is_prefill = false;
  // one speculative draft/verify round (ISSUE 13): the runner drives
  // the whole round (draft burst + width-k verify + rollback) and
  // answers DECODE_SPEC_REP itself
  bool is_spec = false;
  uint64_t session = 0;
  int64_t token = 0;
  // ---- request tracing (ptpu_trace) ----
  uint64_t wire_tid = 0;   // client-sent trace id (echoed in replies)
  uint64_t trace_id = 0;   // effective id (0 = spans not recorded)
  int64_t t_read_us = 0;   // frame bytes first read off the socket
  int64_t t_deq_us = 0;    // popped from the batcher queue
};

// Always-on counters/histograms (csrc/ptpu_stats.h relaxed atomics).
// Connection-lifecycle counters live in the embedded net-core stats.
struct SvStats {
  // req_errors answers INFER requests (the req_balance law's error
  // term); op_errors answers decode/meta ops; err_frames is the
  // total — exactly their sum (err_split law, csrc/ptpu_invar.h)
  ptpu::Counter requests, replies, req_errors, op_errors, batches,
      batched_requests, batched_rows, bucket_miss, full_flushes,
      deadline_flushes, bytes_in, bytes_out, err_frames, proto_errors;
  // CPU microseconds this plane burned handling requests (parse +
  // batch gather + run bookkeeping + reply build; ThreadCpuUs deltas,
  // ISSUE 17). cpu_us / requests is the benches' cycles-per-request
  // column — the perf metric wall time cannot see on a
  // loopback-bandwidth-capped box.
  ptpu::Counter cpu_us;
  ptpu::Histogram queue_depth, batch_fill, e2e_us, run_us;

  void Reset() {
    cpu_us.Reset();
    // Invariant-preserving reset (ISSUE 20): requests in flight at
    // reset time have been counted but not yet answered, so zeroing
    // would leave requests != replies + req_errors FOREVER after.
    // Rebasing both sides of the req_balance law by the same amount
    // (completed work so far) preserves it by construction — no
    // multi-counter atomic snapshot needed, racing traffic cancels.
    // Post-reset, `requests` reads as in-flight + accepted-since.
    const uint64_t rep_base = replies.Get();
    const uint64_t err_base = req_errors.Get();
    const uint64_t op_base = op_errors.Get();
    requests.Rebase(rep_base + err_base);
    replies.Rebase(rep_base);
    req_errors.Rebase(err_base);
    // err_split law: err_frames == req_errors + op_errors — rebase
    // the total by the sum of the bases taken from its terms
    op_errors.Rebase(op_base);
    err_frames.Rebase(err_base + op_base);
    batches.Reset();
    batched_requests.Reset();
    batched_rows.Reset();
    bucket_miss.Reset();
    full_flushes.Reset();
    deadline_flushes.Reset();
    bytes_in.Reset();
    bytes_out.Reset();
    proto_errors.Reset();
    queue_depth.Reset();
    batch_fill.Reset();
    e2e_us.Reset();
    run_us.Reset();
  }
};

/* Shadow-mirror counters (production drills): sampled INFER batches
 * re-run on a second loaded artifact (PTPU_SHADOW_MODEL) with output
 * + latency diffing — the safety check a hot model swap rides.
 * Everything here is u64 (diffs in 1e-9 units) so the `shadow` stats
 * object renders through the /metrics Prometheus walker unchanged. */
struct ShadowStats {
  ptpu::Counter batches;             // mirrored batches run
  ptpu::Counter requests;            // requests inside them
  ptpu::Counter mismatched_batches;  // diff > tol or shape mismatch
  ptpu::Counter run_errors;          // shadow alloc/run failures
  ptpu::Counter primary_run_us;      // primary run_us, mirrored only
  ptpu::Counter shadow_run_us;       // shadow run_us (latency diff)
  std::atomic<uint64_t> max_abs_diff_e9{0};  // worst |Δ| seen, 1e-9

  void Reset() {
    batches.Reset();
    requests.Reset();
    mismatched_batches.Reset();
    run_errors.Reset();
    primary_run_us.Reset();
    shadow_run_us.Reset();
    max_abs_diff_e9.store(0, std::memory_order_relaxed);
  }
};

/* Dynamic micro-batcher: a bounded FIFO request queue drained by N
 * instance workers. A worker flushes when `max_batch` rows are queued
 * or `deadline_us` has elapsed since the OLDEST queued request —
 * batch-1 latency under light load never exceeds the deadline, and
 * under heavy load batches fill before the timer matters. Whole
 * requests only (no splitting), strictly FIFO, so de-muxed replies
 * preserve per-connection submission order. The runner is injected:
 * the server hands the stitched batch to a predictor instance; the
 * selftest injects a recording fake. stop() drains: workers keep
 * flushing until the queue is empty (graceful-stop requests still
 * answer), and only enqueues arriving after stop() see "server
 * stopping". */
class SvBatcher {
 public:
  using Runner = std::function<void(int instance,
                                    std::vector<SvRequest>& batch)>;

  SvBatcher(int64_t max_batch, int64_t deadline_us, int instances,
            SvStats* stats, Runner runner)
      : max_batch_(max_batch),
        deadline_us_(deadline_us),
        max_queue_rows_(std::max<int64_t>(64, 16 * max_batch)),
        stats_(stats),
        runner_(std::move(runner)) {
    for (int i = 0; i < instances; ++i)
      workers_.emplace_back([this, i] { worker(i); });
  }

  ~SvBatcher() { stop(); }

  bool enqueue(SvRequest&& r, std::string* why) {
    ptpu::UniqueLock l(mu_);
    if (stop_) {
      if (why) *why = "server stopping";
      return false;
    }
    if (r.rows < 1 || r.rows > max_batch_) {
      if (why)
        *why = "request rows " + std::to_string(r.rows) +
               " outside [1, max_batch=" + std::to_string(max_batch_) +
               "]";
      return false;
    }
    if (rows_queued_ + r.rows > max_queue_rows_) {
      // bounded backpressure: a flood of producers must not grow the
      // queue (and its payload copies) without limit
      if (why) *why = "request queue full";
      return false;
    }
    rows_queued_ += r.rows;
    q_.push_back(std::move(r));
    stats_->queue_depth.Observe(uint64_t(q_.size()));
    PTPU_SCHED_POINT();  // request queued, worker wakeup not yet sent
    cv_.notify_one();
    return true;
  }

  // stop workers AFTER they drain the queue; anything still queued
  // when they exit (a wedged runner) is returned to the caller, which
  // errors it out before closing connections
  std::deque<SvRequest> stop() {
    {
      ptpu::MutexLock l(mu_);
      stop_ = true;
    }
    PTPU_SCHED_POINT();  // stop flagged, drain wakeup not yet sent
    cv_.notify_all();
    for (auto& t : workers_)
      if (t.joinable()) t.join();
    workers_.clear();
    ptpu::MutexLock l(mu_);
    rows_queued_ = 0;
    return std::move(q_);
  }

  int64_t queued_rows() const {
    ptpu::MutexLock l(mu_);
    return rows_queued_;
  }

 private:
  void worker(int instance) {
    ptpu::UniqueLock l(mu_);
    for (;;) {
      cv_.wait(l, [&] { return stop_ || !q_.empty(); });
      if (q_.empty()) {
        if (stop_) return;
        continue;
      }
      // wait for the batch to fill, but never past the oldest
      // request's deadline
      const int64_t deadline = q_.front().t_enq_us + deadline_us_;
      while (!stop_ && rows_queued_ < max_batch_) {
        const int64_t now = ptpu::NowUs();
        if (now >= deadline) break;
        ptpu::CvWaitForUs(cv_, l, deadline - now);
        if (q_.empty()) break;  // another instance drained it
      }
      if (q_.empty()) {
        if (stop_) return;
        continue;
      }
      std::vector<SvRequest> batch;
      int64_t rows = 0;
      while (!q_.empty() && rows + q_.front().rows <= max_batch_) {
        rows += q_.front().rows;
        batch.push_back(std::move(q_.front()));
        q_.pop_front();
      }
      rows_queued_ -= rows;
      (rows >= max_batch_ ? stats_->full_flushes
                          : stats_->deadline_flushes)
          .Add(1);
      stats_->batches.Add(1);
      stats_->batched_requests.Add(batch.size());
      stats_->batched_rows.Add(uint64_t(rows));
      stats_->batch_fill.Observe(uint64_t(rows));
      if (!q_.empty()) {
        PTPU_SCHED_POINT();  // leftover work, sibling not yet woken
        cv_.notify_one();
      }
      l.unlock();
      // runners take predictor + net locks and must enter lock-free
      PTPU_LOCKDEP_ASSERT_NO_LOCKS("the batcher runner");
      runner_(instance, batch);
      l.lock();
    }
  }

  const int64_t max_batch_, deadline_us_, max_queue_rows_;
  SvStats* stats_;
  Runner runner_;
  mutable ptpu::Mutex mu_{kLockSvBatcher};
  ptpu::CondVar cv_;
  std::deque<SvRequest> q_;
  int64_t rows_queued_ = 0;
  bool stop_ = false;
  std::vector<std::thread> workers_;
};

// model input signature, captured once from the bucket-1 predictor
struct SvInputSig {
  std::string name;
  int dtype = SV_F32;
  std::vector<int64_t> tail;  // dims past the batch axis
  int64_t row_elems = 1;
};

struct SvInstance {
  void* pool = nullptr;                       // ptpu_workpool handle
  std::map<int64_t, PTPU_Predictor*> buckets;  // batch size -> handle
  // NUMA node this instance is placed on (-1: topology probe off —
  // single-node box or PTPU_TOPO=0 — placement fully disabled)
  int node = -1;

  ~SvInstance() {
    for (auto& kv : buckets) ptpu_predictor_destroy(kv.second);
    if (pool) ptpu_workpool_destroy(pool);
  }
};

/* Scope-aggregates the calling thread's consumed CPU time into a
 * plane's cpu_us counter (ISSUE 17 cycles-per-request telemetry).
 * `c` may be retargeted before the scope closes — OnFrame starts on
 * the INFER plane and switches to the decode plane once the tag is
 * known. */
struct SvCpuScope {
  explicit SvCpuScope(ptpu::Counter* counter)
      : c(counter), t0(ptpu::ThreadCpuUs()) {}
  ~SvCpuScope() { c->Add(uint64_t(ptpu::ThreadCpuUs() - t0)); }
  ptpu::Counter* c;
  int64_t t0;
};

/* Refcounted reply pin (ISSUE 17b): holds the batch's detached
 * predictor outputs (every reply's payload segments point straight
 * into them) plus the small owned metadata chunks that interleave
 * with payload segments when a model has >1 output. One pin is shared
 * by every reply of a batch; the net core drops its reference when a
 * conn flushes (or abandons) its frame's last byte, and the LAST
 * release returns the output storage to the predictor's holder pool. */
struct SvReplyPin {
  void* opin = nullptr;                        // ptpu_outputs_pin_*
  std::vector<std::vector<uint8_t>> meta;      // [ndim][dims] chunks
  ~SvReplyPin() {
    if (opin) ptpu_outputs_pin_release(opin);
  }
};

// decode-plane counters (rendered under "decode" in stats_json; the
// PS twin-registry checker only covers the PS renderers, so these are
// C-only by construction)
struct DecStats {
  ptpu::Counter opens, closes, evictions, steps, replies, batches;
  // paged-engine counters (r12): OPEN2 prompts, prompt tokens
  // prefilled by compute vs adopted from the prefix cache, forks,
  // and steps answered "kv pool exhausted" (backpressure, retryable)
  ptpu::Counter prefills, prefill_tokens, prefill_adopted, forks,
      pool_exhausted, bucket_miss;
  // speculative-decoding counters (ISSUE 13): rounds run, draft
  // tokens proposed/accepted, tokens committed via spec (incl. the
  // per-round bonus/correction token), width-1 draft steps executed,
  // and rounds that fell back to a plain target step (context end)
  ptpu::Counter spec_rounds, spec_proposed, spec_accepted,
      spec_tokens, spec_draft_steps, spec_fallbacks;
  // KV tiering counters (ISSUE 19): sessions hibernated to the spill
  // tier instead of tombstone-evicted, transparent restores on the
  // next op, and steps answered "kv spill exhausted" (retryable, the
  // spill-tier twin of pool_exhausted)
  ptpu::Counter hibernates, restores, spill_exhausted;
  // decode-plane CPU microseconds (same contract as SvStats::cpu_us)
  ptpu::Counter cpu_us;
  ptpu::Histogram run_us, batch_fill, restore_us;
  void Reset() {
    cpu_us.Reset();
    // Invariant-preserving reset (ISSUE 20), same construction as
    // SvStats: rebase both sides of the session_balance law
    //   opens == closes + evictions + live gauges
    // by completed exits so far; live/hibernated sessions carry over
    // into the post-reset ledger. hibernates/restores rebase by the
    // same amount (restores so far) to keep hibernate_flow, and
    // forks zeroes (every fork also bumps opens, so forks_are_opens
    // survives any base).
    const uint64_t close_base = closes.Get();
    const uint64_t evict_base = evictions.Get();
    opens.Rebase(close_base + evict_base);
    closes.Rebase(close_base);
    evictions.Rebase(evict_base);
    const uint64_t restore_base = restores.Get();
    hibernates.Rebase(restore_base);
    restores.Rebase(restore_base);
    steps.Reset();
    replies.Reset();
    batches.Reset();
    prefills.Reset();
    prefill_tokens.Reset();
    prefill_adopted.Reset();
    forks.Reset();
    pool_exhausted.Reset();
    bucket_miss.Reset();
    spec_rounds.Reset();
    spec_proposed.Reset();
    spec_accepted.Reset();
    spec_tokens.Reset();
    spec_draft_steps.Reset();
    spec_fallbacks.Reset();
    spill_exhausted.Reset();
    run_us.Reset();
    batch_fill.Reset();
    restore_us.Reset();
  }
};

/* ---- speculative-decoding sampler (ISSUE 13) ----------------------
 * The acceptance rule needs a deterministic, seedable RNG and exact
 * softmax/argmax/CDF primitives in C. splitmix64 is the generator
 * (one u64 of state per session, seeded from the wire); uniforms are
 * the standard 53-bit mantissa draw. Softmax accumulates in double so
 * the sampled distribution matches numpy's float64 softmax of the
 * same float32 logits to ~1ulp. argmax ties break to the LOWEST
 * index — np.argmax's rule, which the greedy parity gate relies on. */
inline uint64_t spec_sm64(uint64_t* s) {
  uint64_t z = (*s += 0x9e3779b97f4a7c15ull);
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ull;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebull;
  return z ^ (z >> 31);
}

inline double spec_u01(uint64_t* s) {
  return double(spec_sm64(s) >> 11) * 0x1.0p-53;
}

inline int64_t spec_argmax(const float* lg, int64_t v) {
  int64_t best = 0;
  for (int64_t i = 1; i < v; ++i)
    if (lg[i] > lg[best]) best = i;
  return best;
}

inline void spec_softmax(const float* lg, int64_t v, float* p) {
  float m = lg[0];
  for (int64_t i = 1; i < v; ++i) m = std::max(m, lg[i]);
  double sum = 0.0;
  for (int64_t i = 0; i < v; ++i) {
    const double e = std::exp(double(lg[i]) - double(m));
    p[i] = float(e);
    sum += e;
  }
  const float inv = float(1.0 / sum);
  for (int64_t i = 0; i < v; ++i) p[i] *= inv;
}

// CDF-walk sample of a (sub-)normalized distribution; `norm` is the
// distribution's own mass so a residual distribution samples exactly
inline int64_t spec_sample(const float* p, int64_t v, double norm,
                           double u) {
  double acc = 0.0;
  const double target = u * norm;
  for (int64_t i = 0; i < v; ++i) {
    acc += double(p[i]);
    if (target < acc) return i;
  }
  // fp tail: return the last index with nonzero mass
  for (int64_t i = v; i-- > 0;)
    if (p[i] > 0.f) return i;
  return v - 1;
}

struct SvServer {
  std::string model_path;
  std::string authkey;
  int port = 0;
  int64_t max_batch = 8;
  int64_t deadline_us = 2000;
  int instances = 2;
  int threads_per_instance = 0;
  // ---- KV-cached decode plane (optional second artifact) ----
  std::string decode_model_path;
  int kv_sessions = 0;             // max sessions; 0 -> env -> default
  PTPU_Predictor* dec_pred = nullptr;   // largest surviving bucket
  void* dec_pool = nullptr;
  int64_t dec_batch = 0;           // decode artifact's baked batch
  int64_t dec_ctx = 0;             // cache positions per session
  int64_t dec_logit_elems = 0;     // logits row width
  std::unique_ptr<SvBatcher> dec_batcher;
  DecStats dstats;
  /* Paged generation engine (ISSUE r12): a step-batch bucket ladder
   * {1, 2, 4, ..., B} of decode predictors re-planned at load (like
   * the INFER ladder, so partial fill stops padding to one baked
   * batch), all attached to ONE shared KvPool — sessions live in the
   * pool, RAM scales with tokens held, prompt prefixes are shared
   * through the pool's prefix cache. PTPU_KV_PAGED=0 falls back to
   * the r9 fixed-slot engine (kv_plan on the single max predictor). */
  bool kv_paged = false;
  PTPU_KvPool* kv_pool = nullptr;
  std::map<int64_t, PTPU_Predictor*> dec_buckets;
  std::vector<int64_t> dec_ladder;
  int64_t prefill_chunk = 16;      // $PTPU_PREFILL_CHUNK, else page
  /* ---- speculative decoding (ISSUE 13) ----
   * Two more artifact planes beside the width-1 target ladder:
   *   draft   a SMALL model's width-1 decode artifact with its OWN
   *           KvPool (different geometry than the target) — proposes
   *           k tokens per round via sequential draft steps, batched
   *           across sessions by the shared decode flush;
   *   verify  the TARGET model exported at width k+1
   *           (models.gpt.export_gpt_decode(width=k+1)) attached to
   *           the SAME pool as the target ladder, so verify passes
   *           read/extend/roll back the very sessions the width-1
   *           steps use.
   * spec_k = verify width - 1, optionally capped by $PTPU_SPEC_K
   * (padding tokens fill the unused verify columns; their rows are
   * rolled back with the rejected suffix). */
  /* ---- KV tiering + session hibernation (ISSUE 19) ----
   * PTPU_KV_SPILL_PATH attaches an mmap'd spill file to the decode
   * pool(s); with it set, the LRU victim in OpenSlotLocked hibernates
   * (pool state serialized, pages spilled or kept by shared ref)
   * instead of tombstone-evicting, and the next DECODE/SPEC op on a
   * hibernated session restores it transparently. Default off. */
  std::string sv_spill_path;       // empty = tiering off
  int64_t sv_spill_max = -1;       // PTPU_KV_SPILL_MAX_BYTES (-1=env)
  std::string prefix_persist_path; // PTPU_KV_PREFIX_PERSIST (empty=off)
  std::string spec_draft_path, spec_verify_path;
  PTPU_KvPool* draft_pool = nullptr;
  std::map<int64_t, PTPU_Predictor*> draft_buckets, ver_buckets;
  std::vector<int64_t> draft_ladder, ver_ladder;
  int64_t draft_batch = 0, draft_ctx = 0, draft_logit_elems = 0;
  int64_t ver_batch = 0, ver_width = 0;
  int64_t spec_k = 0;              // 0 = spec disabled
  /* Per-session speculative state, owned by the WireSession. The
   * committed vector is the full token history (prompt + generated);
   * its LAST entry is committed-but-unfed — the round invariant:
   * target fed len == committed.size() - 1. draft_len tracks the
   * draft session's fed positions (lags behind during chunked
   * catch-up; runs 1 ahead of a trim after a fully-accepted round). */
  struct SpecState {
    bool sample = false;
    uint64_t rng = 0;              // splitmix64 state (wire seed)
    int draft_slot = -1;           // session in draft_pool
    std::vector<int64_t> committed;
    int64_t draft_len = 0;
    int64_t prompt_len = 0;
    bool draft_published = false;  // draft prompt pages in its cache
  };
  /* One in-flight prompt prefill per OPEN2 (keyed by wire session,
   * guarded by sess_mu_): `next` tokens admitted into the decode
   * batcher so far (at most `prefill_chunk` beyond `done`), `done`
   * tokens whose step completed. The final token's step answers
   * DECODE_OPEN_REP with its logits and publishes the prompt's full
   * pages into the pool's prefix cache. */
  struct PrefillJob {
    uint64_t sess = 0;
    uint64_t rid = 0;
    ptpu::net::ConnPtr conn;
    uint64_t wire_tid = 0;
    uint64_t trace_id = 0;
    int64_t t_read_us = 0, t_enq_us = 0;
    std::vector<int64_t> tokens;
    int64_t next = 0;     // tokens admitted (adopted ones count)
    int64_t done = 0;     // tokens stepped (adopted ones count)
    int64_t adopted = 0;
    // SPEC_OPEN prefill: completion picks the first token from the
    // last prompt logits and answers DECODE_SPEC_REP instead
    bool spec = false;
  };
  std::map<uint64_t, std::unique_ptr<PrefillJob>> prefills_;
  // jobs whose next chunk could not enqueue (batcher full): retried
  // at the start of every decode flush
  std::vector<uint64_t> prefill_resume_;
  // spec rounds parked mid-catch-up by a full queue (same retry)
  std::vector<SvRequest> spec_resume_;
  /* Wire-session registry, two locks with a fixed order kv_mu_ ->
   * sess_mu_:
   *   sess_mu_  the registry map only — always held briefly.
   *   kv_mu_    every ptpu_predictor_kv_* / decode_step call (the
   *             predictor is thread-compatible; open/close arrive on
   *             event threads while steps run on the decode worker).
   * The split keeps the event loops responsive: a closing INFER-only
   * connection checks session ownership under sess_mu_ alone and
   * never waits out a running decode batch; only decode-plane ops
   * (open/close/step of sessions) serialize on kv_mu_. slot == -1 is
   * an eviction tombstone: later steps on that session answer
   * "evicted" instead of "unknown" — unless `hib` is non-empty, in
   * which case the session is HIBERNATED (ISSUE 19): its pool state
   * lives in the spill tier and the next step restores it
   * transparently. */
  struct WireSession {
    int slot = -1;
    uint64_t last_us = 0;
    const void* owner = nullptr;   // opening conn (freed on conn close)
    std::unique_ptr<SpecState> spec;  // speculative sessions only
    // hibernation records (ISSUE 19): opaque pool handles from
    // ptpu_kvpool_hibernate, cross-validated by the pool on restore.
    // hib covers the target session; hib_draft the spec draft twin.
    std::vector<uint8_t> hib, hib_draft;
    // set while this session's pool sid is collected into the decode
    // run being assembled: a mid-run restore's make-room pass must
    // not hibernate/evict it out from under the collected sid
    bool pinned = false;
  };
  ptpu::Mutex kv_mu_{kLockSvKv};
  ptpu::Mutex sess_mu_{kLockSvSess};
  std::map<uint64_t, WireSession> sessions_;
  uint64_t next_session_ = 1;
  // the decode batcher keeps its own batcher-stats block so the INFER
  // plane's exact counters (batches, batched_requests, queue_depth)
  // stay decode-free
  SvStats dec_bstats;
  std::vector<int64_t> ladder;
  std::vector<SvInputSig> sig;
  int n_outputs = 0;
  std::string meta_json;

  /* ---- shadow traffic plane (production drills) ----
   * PTPU_SHADOW_MODEL loads a SECOND artifact next to the primary
   * ladder; 1-in-PTPU_SHADOW_SAMPLE INFER batches re-run on it after
   * their primary replies are queued, and outputs/latency diff into
   * sstats (surfaced as the `shadow` stats object + GET /shadowz).
   * One bucket set shared by every instance worker, serialized on
   * shadow_mu_ — mirroring is sampled diagnostics, not a second
   * serving plane, so one run at a time is the point. */
  std::string shadow_model_path;
  int64_t shadow_sample = 1;       // PTPU_SHADOW_SAMPLE: 1-in-N batches
  double shadow_tol = 1e-5;        // PTPU_SHADOW_TOL: max |Δ| allowed
  void* shadow_pool = nullptr;
  std::map<int64_t, PTPU_Predictor*> shadow_buckets;
  std::atomic<uint64_t> shadow_ctr_{0};
  ptpu::Mutex shadow_mu_{kLockSvShadow};
  ShadowStats sstats;

  std::vector<std::unique_ptr<SvInstance>> insts;
  std::unique_ptr<SvBatcher> batcher;
  SvStats stats;
  ptpu::net::Stats net;
  std::unique_ptr<ptpu::net::Server> net_srv;
  std::atomic<bool> stop{false};
  // two-phase shutdown: drain_begin() stops the framed listener and
  // flips /healthz to 503 "draining" while in-flight + existing-conn
  // requests still answer; Stop() completes the teardown
  std::atomic<bool> draining{false};
  int http_port_want = -1;       // start3 http_port (env can override)
  std::atomic<uint64_t> batch_seq{0};  // trace arg of batch-side spans

  ~SvServer() { Stop(); }

  // ---------------------------------------------------------- start
  // throws std::runtime_error on any setup failure
  void Start(int want_port, int loopback_only) {
    char err[512] = {0};
    // bucket ladder: {1, 2, 4, ..., max_batch}; each predictor is
    // re-planned for its bucket so batched runs stay zero-alloc
    for (int64_t b = 1; b < max_batch; b *= 2) ladder.push_back(b);
    ladder.push_back(max_batch);

    const int hw = [] {
      const char* e = std::getenv("PTPU_PREDICTOR_THREADS");
      int v = e ? std::atoi(e) : 0;
      if (v <= 0) v = int(std::thread::hardware_concurrency());
      return std::max(1, v);
    }();
    if (threads_per_instance <= 0)
      threads_per_instance = std::max(1, hw / std::max(1, instances));

    for (int i = 0; i < instances; ++i) {
      auto inst = std::unique_ptr<SvInstance>(new SvInstance());
      /* Topology-aware placement (ISSUE 17c): round-robin instances
       * over NUMA nodes. The creating thread binds to the node FIRST
       * so the instance's worker threads inherit the mask AND the
       * bucket predictors' planned arenas first-touch node-local
       * pages; the batcher worker that runs this instance binds
       * itself on its first batch. node == -1 (single-node box or
       * PTPU_TOPO=0) makes every call below a no-op — byte-identical
       * to the unplaced build. */
      inst->node = ptpu::topo::NodeOfInstance(i);
      ptpu::topo::BindCurrentThreadToNode(inst->node);
      inst->pool = ptpu_workpool_create_bound(threads_per_instance,
                                              inst->node);
      for (int64_t b : ladder) {
        PTPU_Predictor* p = ptpu_predictor_create_opts(
            model_path.c_str(), b, 0, err, sizeof(err));
        if (!p) {
          ptpu::topo::UnbindCurrentThread();
          throw std::runtime_error(std::string("bucket ") +
                                   std::to_string(b) + ": " + err);
        }
        ptpu_predictor_set_pool(p, inst->pool);
        inst->buckets[b] = p;
      }
      ptpu::topo::UnbindCurrentThread();
      insts.push_back(std::move(inst));
    }

    // input signature from the bucket-1 predictor (tail dims shared
    // by every bucket; the batch axis is the override)
    PTPU_Predictor* p1 = insts[0]->buckets[1];
    const int nin = ptpu_predictor_num_inputs(p1);
    if (nin <= 0) throw std::runtime_error("model has no inputs");
    for (int i = 0; i < nin; ++i) {
      SvInputSig s;
      s.name = ptpu_predictor_input_name(p1, i);
      s.dtype = ptpu_predictor_input_dtype(p1, i);
      if (s.dtype == 11) s.dtype = SV_F32;  // f64 parses as f32
      if (sv_dtype_size(s.dtype) == 0)
        throw std::runtime_error("input '" + s.name +
                                 "' has unsupported dtype " +
                                 std::to_string(s.dtype));
      const int nd = ptpu_predictor_input_ndim(p1, i);
      const int64_t* d = ptpu_predictor_input_dims(p1, i);
      if (nd < 1 || !d)
        throw std::runtime_error("input '" + s.name +
                                 "' needs a batch axis to serve");
      for (int k = 1; k < nd; ++k) {
        if (d[k] <= 0)
          throw std::runtime_error("input '" + s.name +
                                   "' has dynamic dims");
        s.tail.push_back(d[k]);
        s.row_elems *= d[k];
      }
      sig.push_back(std::move(s));
    }
    n_outputs = ptpu_predictor_num_outputs(p1);

    /* Probe every bucket with a zero batch once: a graph that is not
     * batch-polymorphic (static Reshape constants baked to the export
     * batch) fails HERE, at load, not on the first live batch. Failed
     * buckets > 1 are dropped and max_batch capped to the largest
     * surviving bucket; a failing bucket 1 fails start. */
    std::vector<int64_t> ok_ladder;
    for (int64_t b : ladder) {
      std::string perr;
      if (ProbeBucket(b, &perr)) {
        ok_ladder.push_back(b);
      } else if (b == 1) {
        throw std::runtime_error("bucket-1 probe failed: " + perr);
      } else {
        for (auto& inst : insts) {
          ptpu_predictor_destroy(inst->buckets[b]);
          inst->buckets.erase(b);
        }
      }
    }
    ladder = ok_ladder;
    max_batch = ladder.back();

    // the bucket probes above executed every (bucket, shape) GEMM, so
    // the per-machine autotuner has probed every shape this ladder
    // can serve — persist the winners once, at start-up (the second
    // start of the same ladder then loads them and probes nothing)
    if (ptpu::tune::Registry::Enabled())
      ptpu::tune::Registry::Inst().SaveIfDirty();

    /* ---- optional shadow plane (production drills): a second
     * artifact built over the SAME surviving ladder, its own worker
     * pool. The shadow model must be signature-compatible with the
     * primary (same inputs/outputs) — a drill that cannot compare is
     * a configuration error, so it fails start loudly. */
    const char* sm = std::getenv("PTPU_SHADOW_MODEL");
    if (sm && *sm) {
      shadow_model_path = sm;
      const char* se = std::getenv("PTPU_SHADOW_SAMPLE");
      if (se && *se) shadow_sample = std::atoll(se);
      if (shadow_sample < 1) shadow_sample = 1;
      const char* te = std::getenv("PTPU_SHADOW_TOL");
      if (te && *te) shadow_tol = std::atof(te);
      if (!(shadow_tol >= 0)) shadow_tol = 1e-5;
      shadow_pool = ptpu_workpool_create(threads_per_instance);
      for (int64_t b : ladder) {
        PTPU_Predictor* sp = ptpu_predictor_create_opts(
            shadow_model_path.c_str(), b, 0, err, sizeof(err));
        if (!sp)
          throw std::runtime_error(std::string("shadow bucket ") +
                                   std::to_string(b) + ": " + err);
        ptpu_predictor_set_pool(sp, shadow_pool);
        shadow_buckets[b] = sp;
      }
      PTPU_Predictor* s1 = shadow_buckets[ladder.front()];
      if (ptpu_predictor_num_inputs(s1) != int(sig.size()) ||
          ptpu_predictor_num_outputs(s1) != n_outputs)
        throw std::runtime_error(
            "shadow model input/output signature differs from the "
            "primary — cannot mirror traffic onto it");
    }

    // ---- optional KV-decode plane: its own predictor (the KV arena
    // lives inside it — sessions are bound to ONE predictor), its own
    // worker sub-pool, and its own micro-batcher instance so decode
    // steps from different sessions batch continuously without mixing
    // into INFER flushes.
    if (!decode_model_path.empty()) {
      const char* pg = std::getenv("PTPU_KV_PAGED");
      kv_paged = !(pg && std::strcmp(pg, "0") == 0);
      const int kv_sessions_arg = kv_sessions;
      if (kv_sessions <= 0) {
        const char* e = std::getenv("PTPU_KV_SESSIONS");
        kv_sessions = e ? std::atoi(e) : 0;
        if (kv_sessions <= 0) kv_sessions = kv_paged ? 4096 : 64;
      }
      dec_pred = ptpu_predictor_create_opts(decode_model_path.c_str(), 0,
                                            0, err, sizeof(err));
      if (!dec_pred)
        throw std::runtime_error(std::string("decode model: ") + err);
      dec_pool = ptpu_workpool_create(threads_per_instance);
      ptpu_predictor_set_pool(dec_pred, dec_pool);
      const int64_t* idd = ptpu_predictor_input_dims(dec_pred, 0);
      const int64_t* cdd = ptpu_predictor_input_dims(dec_pred, 2);
      if (!idd || !cdd)
        throw std::runtime_error("decode model: missing input dims");
      dec_batch = idd[0];
      dec_ctx = cdd[1];
      if (kv_paged) {
        /* Pool sizing: an explicit kv_sessions argument keeps the old
         * capacity promise (N sessions x full context always fit);
         * the default pool spends the r9 envelope (64 x context) on
         * however many sessions actually fit their tokens in it. */
        int64_t page = 16;
        if (const char* e = std::getenv("PTPU_KV_PAGE"))
          if (std::atoll(e) > 0) page = std::atoll(e);
        int64_t pool_tokens = 0;
        if (const char* e = std::getenv("PTPU_KV_POOL_TOKENS"))
          pool_tokens = std::atoll(e);
        if (pool_tokens <= 0)
          pool_tokens = (kv_sessions_arg > 0 ? int64_t(kv_sessions_arg)
                                             : 64) *
                        ((dec_ctx + page - 1) / page) * page;
        kv_pool = ptpu_kvpool_create(pool_tokens, int(page),
                                     kv_sessions, -1, err, sizeof(err));
        if (!kv_pool)
          throw std::runtime_error(std::string("kvpool: ") + err);
        if (ptpu_predictor_kv_attach(dec_pred, kv_pool, err,
                                     sizeof(err)) != 0)
          throw std::runtime_error(std::string("kv_attach: ") + err);
        /* ---- KV tiering (ISSUE 19): attach the spill tier and warm
         * the prefix cache. Both default off. The kv_attach above
         * fixed the pool geometry, which both file formats pin. */
        if (sv_spill_path.empty())
          if (const char* e = std::getenv("PTPU_KV_SPILL_PATH"))
            sv_spill_path = e;
        if (!sv_spill_path.empty() &&
            ptpu_kvpool_spill_attach(kv_pool, sv_spill_path.c_str(),
                                     sv_spill_max, err,
                                     sizeof(err)) != 0)
          throw std::runtime_error(std::string("kv spill: ") + err);
        if (prefix_persist_path.empty())
          if (const char* e = std::getenv("PTPU_KV_PREFIX_PERSIST"))
            prefix_persist_path = e;
        if (!prefix_persist_path.empty())
          // best-effort warm: a malformed/missing file only counts a
          // reject in the pool (the cache can miss, never lie)
          ptpu_kvpool_prefix_load(kv_pool, prefix_persist_path.c_str(),
                                  err, sizeof(err));
        dec_buckets[dec_batch] = dec_pred;
        // step-batch ladder below the baked batch, re-planned at load
        for (int64_t b2 = 1; b2 < dec_batch; b2 *= 2) {
          PTPU_Predictor* p2 = ptpu_predictor_create_opts(
              decode_model_path.c_str(), b2, 0, err, sizeof(err));
          if (!p2)
            throw std::runtime_error(std::string("decode bucket ") +
                                     std::to_string(b2) + ": " + err);
          ptpu_predictor_set_pool(p2, dec_pool);
          if (ptpu_predictor_kv_attach(p2, kv_pool, err,
                                       sizeof(err)) != 0) {
            ptpu_predictor_destroy(p2);
            throw std::runtime_error(std::string("decode bucket ") +
                                     std::to_string(b2) +
                                     " kv_attach: " + err);
          }
          dec_buckets[b2] = p2;
        }
        prefill_chunk = 16;
        {
          const char* e = std::getenv("PTPU_KV_PAGE");
          if (e && std::atoi(e) > 0) prefill_chunk = std::atoi(e);
          if (const char* c = std::getenv("PTPU_PREFILL_CHUNK"))
            if (std::atoi(c) > 0) prefill_chunk = std::atoi(c);
        }
      } else {
        if (ptpu_predictor_kv_plan(dec_pred, kv_sessions, err,
                                   sizeof(err)) != 0)
          throw std::runtime_error(std::string("kv_plan: ") + err);
        dec_buckets[dec_batch] = dec_pred;
      }
      /* Probe every decode bucket with one step now: a malformed (or
       * non-batch-polymorphic) artifact fails at start, not on the
       * first live session; the max bucket also fixes the logits row
       * width for DECODE_REP frames. Failed buckets < B are dropped;
       * a failing max bucket fails start. */
      for (auto it = dec_buckets.begin(); it != dec_buckets.end();) {
        PTPU_Predictor* p2 = it->second;
        const int sid = ptpu_predictor_kv_open(p2);
        if (sid < 0) throw std::runtime_error("kv probe: no session");
        const int64_t sids[1] = {sid}, toks[1] = {0};
        std::string perr;
        if (ptpu_predictor_decode_step(p2, sids, toks, 1, err,
                                       sizeof(err)) != 0)
          perr = err;
        if (perr.empty()) {
          const int nd = ptpu_predictor_output_ndim(p2, 0);
          const int64_t* od = ptpu_predictor_output_dims(p2, 0);
          if (nd < 1 || !od || od[0] != it->first) {
            perr = "logits output lost the batch axis";
          } else if (it->first == dec_batch) {
            dec_logit_elems = 1;
            for (int k = 1; k < nd; ++k) dec_logit_elems *= od[k];
          }
        }
        ptpu_predictor_kv_close(p2, sid);
        if (perr.empty()) {
          ++it;
        } else if (it->first == dec_batch) {
          throw std::runtime_error("decode probe: " + perr);
        } else {
          ptpu_predictor_destroy(p2);
          it = dec_buckets.erase(it);
        }
      }
      for (const auto& kv2 : dec_buckets)
        dec_ladder.push_back(kv2.first);

      // ---- speculative decoding plane (ISSUE 13) ----
      if (!spec_draft_path.empty() || !spec_verify_path.empty()) {
        if (spec_draft_path.empty() || spec_verify_path.empty())
          throw std::runtime_error(
              "speculative decoding needs BOTH spec_draft_model and "
              "spec_verify_model");
        if (!kv_paged || !kv_pool)
          throw std::runtime_error(
              "speculative decoding needs the paged KV engine "
              "(unset PTPU_KV_PAGED=0)");
        if (ptpu_predictor_kv_width(dec_pred) != 1)
          throw std::runtime_error(
              "decode_model must be a width-1 step artifact");
        // probe one decode bucket of either spec plane: open a
        // session, feed `width` zero tokens, validate the logits
        // batch axis, report the per-row logits element count
        const auto probe_spec = [&](PTPU_Predictor* p2, int64_t rows,
                                    int64_t width, int64_t* row_elems,
                                    std::string* perr) {
          const int sid = ptpu_predictor_kv_open(p2);
          if (sid < 0) {
            *perr = "no probe session";
            return false;
          }
          std::vector<int64_t> sids(1, sid), toks(size_t(width), 0);
          char perr2[512] = {0};
          bool ok = ptpu_predictor_decode_step(p2, sids.data(),
                                               toks.data(), 1, perr2,
                                               sizeof(perr2)) == 0;
          if (!ok) {
            *perr = perr2;
          } else {
            const int nd = ptpu_predictor_output_ndim(p2, 0);
            const int64_t* od = ptpu_predictor_output_dims(p2, 0);
            if (nd < 1 || !od || od[0] != rows) {
              *perr = "logits output lost the batch axis";
              ok = false;
            } else if (row_elems) {
              *row_elems = 1;
              for (int k = 1; k < nd; ++k) *row_elems *= od[k];
            }
          }
          ptpu_predictor_kv_close(p2, sid);
          return ok;
        };

        /* Verify plane: the TARGET model exported at width k+1,
         * attached to the SAME pool as the width-1 ladder — a verify
         * pass extends (and kv_trim rolls back) the very sessions the
         * plain steps feed. Own step-batch ladder below its baked
         * batch, batch-repaired exactly like the dec ladder. */
        PTPU_Predictor* vp = ptpu_predictor_create_opts(
            spec_verify_path.c_str(), 0, 0, err, sizeof(err));
        if (!vp)
          throw std::runtime_error(std::string("spec verify model: ") +
                                   err);
        ptpu_predictor_set_pool(vp, dec_pool);
        if (ptpu_predictor_kv_attach(vp, kv_pool, err,
                                     sizeof(err)) != 0) {
          ptpu_predictor_destroy(vp);
          throw std::runtime_error(
              std::string("spec verify kv_attach: ") + err);
        }
        const int64_t* vdd = ptpu_predictor_input_dims(vp, 0);
        ver_batch = vdd ? vdd[0] : 0;
        ver_width = ptpu_predictor_kv_width(vp);
        if (ver_width < 2) {
          ptpu_predictor_destroy(vp);
          throw std::runtime_error(
              "spec_verify_model must be a width >= 2 artifact "
              "(models.gpt.export_gpt_decode(width=k+1))");
        }
        ver_buckets[ver_batch] = vp;
        for (int64_t b2 = 1; b2 < ver_batch; b2 *= 2) {
          PTPU_Predictor* p2 = ptpu_predictor_create_opts(
              spec_verify_path.c_str(), b2, 0, err, sizeof(err));
          if (!p2)
            throw std::runtime_error(std::string("verify bucket ") +
                                     std::to_string(b2) + ": " + err);
          ptpu_predictor_set_pool(p2, dec_pool);
          if (ptpu_predictor_kv_attach(p2, kv_pool, err,
                                       sizeof(err)) != 0) {
            ptpu_predictor_destroy(p2);
            throw std::runtime_error(std::string("verify bucket ") +
                                     std::to_string(b2) +
                                     " kv_attach: " + err);
          }
          ver_buckets[b2] = p2;
        }
        int64_t ver_row_elems = 0;
        for (auto it = ver_buckets.begin(); it != ver_buckets.end();) {
          std::string perr;
          int64_t re = 0;
          if (probe_spec(it->second, it->first, ver_width, &re,
                         &perr)) {
            if (it->first == ver_batch) ver_row_elems = re;
            ++it;
          } else if (it->first == ver_batch) {
            throw std::runtime_error("verify probe: " + perr);
          } else {
            ptpu_predictor_destroy(it->second);
            it = ver_buckets.erase(it);
          }
        }
        if (ver_row_elems != ver_width * dec_logit_elems)
          throw std::runtime_error(
              "spec_verify_model logits are not [B, W, vocab] for the "
              "decode_model's vocab");
        for (const auto& kv2 : ver_buckets)
          ver_ladder.push_back(kv2.first);

        /* Draft plane: a small model's width-1 artifact with its OWN
         * pool (different [P,H,D,layers] geometry than the target).
         * The draft session mirrors the committed token history; its
         * prefix cache makes repeated spec opens of a shared prompt
         * cheap on the draft side too. */
        PTPU_Predictor* dp = ptpu_predictor_create_opts(
            spec_draft_path.c_str(), 0, 0, err, sizeof(err));
        if (!dp)
          throw std::runtime_error(std::string("spec draft model: ") +
                                   err);
        const int64_t* ddd = ptpu_predictor_input_dims(dp, 0);
        const int64_t* dcd = ptpu_predictor_input_dims(dp, 2);
        draft_batch = ddd ? ddd[0] : 0;
        draft_ctx = dcd ? dcd[1] : 0;
        int64_t dpage = 16;
        if (const char* e = std::getenv("PTPU_KV_PAGE"))
          if (std::atoll(e) > 0) dpage = std::atoll(e);
        const int64_t dpool_tokens =
            (kv_sessions_arg > 0 ? int64_t(kv_sessions_arg) : 64) *
            ((draft_ctx + dpage - 1) / dpage) * dpage;
        draft_pool = ptpu_kvpool_create(dpool_tokens, int(dpage),
                                       kv_sessions, -1, err,
                                       sizeof(err));
        if (!draft_pool) {
          ptpu_predictor_destroy(dp);
          throw std::runtime_error(std::string("draft kvpool: ") + err);
        }
        ptpu_predictor_set_pool(dp, dec_pool);
        if (ptpu_predictor_kv_attach(dp, draft_pool, err,
                                     sizeof(err)) != 0) {
          ptpu_predictor_destroy(dp);
          throw std::runtime_error(
              std::string("spec draft kv_attach: ") + err);
        }
        if (ptpu_predictor_kv_width(dp) != 1) {
          ptpu_predictor_destroy(dp);
          throw std::runtime_error(
              "spec_draft_model must be a width-1 step artifact");
        }
        // spill tier for the draft twin (ISSUE 19): spec sessions
        // hibernate both planes, so the draft pool needs its own
        // spill file (different geometry than the target's)
        if (!sv_spill_path.empty() &&
            ptpu_kvpool_spill_attach(draft_pool,
                                     (sv_spill_path + ".draft").c_str(),
                                     sv_spill_max, err,
                                     sizeof(err)) != 0) {
          ptpu_predictor_destroy(dp);
          throw std::runtime_error(std::string("draft kv spill: ") +
                                   err);
        }
        draft_buckets[draft_batch] = dp;
        for (int64_t b2 = 1; b2 < draft_batch; b2 *= 2) {
          PTPU_Predictor* p2 = ptpu_predictor_create_opts(
              spec_draft_path.c_str(), b2, 0, err, sizeof(err));
          if (!p2)
            throw std::runtime_error(std::string("draft bucket ") +
                                     std::to_string(b2) + ": " + err);
          ptpu_predictor_set_pool(p2, dec_pool);
          if (ptpu_predictor_kv_attach(p2, draft_pool, err,
                                       sizeof(err)) != 0) {
            ptpu_predictor_destroy(p2);
            throw std::runtime_error(std::string("draft bucket ") +
                                     std::to_string(b2) +
                                     " kv_attach: " + err);
          }
          draft_buckets[b2] = p2;
        }
        for (auto it = draft_buckets.begin();
             it != draft_buckets.end();) {
          std::string perr;
          int64_t re = 0;
          if (probe_spec(it->second, it->first, 1, &re, &perr)) {
            if (it->first == draft_batch) draft_logit_elems = re;
            ++it;
          } else if (it->first == draft_batch) {
            throw std::runtime_error("draft probe: " + perr);
          } else {
            ptpu_predictor_destroy(it->second);
            it = draft_buckets.erase(it);
          }
        }
        if (draft_logit_elems != dec_logit_elems)
          throw std::runtime_error(
              "spec_draft_model vocab (" +
              std::to_string(draft_logit_elems) +
              ") != decode_model vocab (" +
              std::to_string(dec_logit_elems) + ")");
        for (const auto& kv2 : draft_buckets)
          draft_ladder.push_back(kv2.first);

        spec_k = ver_width - 1;
        if (const char* e = std::getenv("PTPU_SPEC_K")) {
          const int64_t v = std::atoll(e);
          if (v > 0 && v < spec_k) spec_k = v;
        }
      }

      dec_batcher.reset(new SvBatcher(
          dec_batch, deadline_us, 1, &dec_bstats,
          [this](int, std::vector<SvRequest>& batch) {
            RunDecode(batch);
          }));
    }

    BuildMetaJson();

    batcher.reset(new SvBatcher(
        max_batch, deadline_us, instances, &stats,
        [this](int instance, std::vector<SvRequest>& batch) {
          RunBatch(instance, batch);
        }));

    ptpu::net::Options opt;
    opt.port = want_port;
    opt.loopback_only = loopback_only != 0;
    opt.authkey = authkey;
    opt.max_frame = kSvMaxFrame;
    opt.http_port = http_port_want;
    opt = ptpu::net::OptionsFromEnv(opt);
    ptpu::net::Callbacks cbs;
    cbs.on_frame = [this](const ptpu::net::ConnPtr& c,
                          const uint8_t* p, uint32_t n) {
      return OnFrame(c, p, n);
    };
    cbs.on_oversize = [this](const ptpu::net::ConnPtr&) {
      stats.proto_errors.Add(1);
    };
    cbs.on_http = [this](const std::string& target) {
      return HandleHttp(target);
    };
    // conn->user stashes a parsed-but-unqueued SvRequest across defer
    // retries (see OnFrame); free it if the conn dies mid-defer. A
    // closing conn also frees every decode session it opened.
    cbs.on_close = [this](const ptpu::net::ConnPtr& c) {
      delete static_cast<SvRequest*>(c->user);
      c->user = nullptr;
      DecodeConnClosed(c.get());
    };
    net_srv.reset(new ptpu::net::Server(opt, std::move(cbs), &net));
    std::string nerr;
    if (!net_srv->Start(&nerr)) {
      net_srv.reset();
      throw std::runtime_error(nerr);
    }
    port = net_srv->port();
  }

  bool ProbeBucket(int64_t b, std::string* perr) {
    char err[512] = {0};
    for (auto& inst : insts) {
      PTPU_Predictor* p = inst->buckets[b];
      for (size_t i = 0; i < sig.size(); ++i) {
        std::vector<int64_t> dims;
        dims.push_back(b);
        dims.insert(dims.end(), sig[i].tail.begin(), sig[i].tail.end());
        const int64_t n = b * sig[i].row_elems;
        int rc;
        if (sig[i].dtype == SV_F32) {
          std::vector<float> z(size_t(n), 0.f);
          rc = ptpu_predictor_set_input(p, sig[i].name.c_str(), z.data(),
                                        dims.data(), int(dims.size()),
                                        err, sizeof(err));
        } else if (sig[i].dtype == SV_I32) {
          std::vector<int32_t> z(size_t(n), 0);
          rc = ptpu_predictor_set_input_i32(p, sig[i].name.c_str(),
                                            z.data(), dims.data(),
                                            int(dims.size()), err,
                                            sizeof(err));
        } else {
          std::vector<int64_t> z(size_t(n), 0);
          rc = ptpu_predictor_set_input_i64(p, sig[i].name.c_str(),
                                            z.data(), dims.data(),
                                            int(dims.size()), err,
                                            sizeof(err));
        }
        if (rc != 0) {
          *perr = err;
          return false;
        }
      }
      if (ptpu_predictor_run(p, err, sizeof(err)) != 0) {
        *perr = err;
        return false;
      }
      // every output must carry the batch on axis 0 or de-muxing
      // replies row-wise would hand clients other requests' data
      for (int o = 0; o < n_outputs; ++o) {
        const int nd = ptpu_predictor_output_ndim(p, o);
        const int64_t* od = ptpu_predictor_output_dims(p, o);
        if (nd < 1 || !od || od[0] != b) {
          *perr = "output " + std::to_string(o) +
                  " does not carry the batch on axis 0";
          return false;
        }
      }
    }
    return true;
  }

  void BuildMetaJson() {
    std::string out = "{\"version\":1,";
    ptpu::AppendJsonU64(&out, "max_batch", uint64_t(max_batch));
    out += ',';
    ptpu::AppendJsonU64(&out, "deadline_us", uint64_t(deadline_us));
    out += ',';
    ptpu::AppendJsonU64(&out, "instances", uint64_t(instances));
    out += ',';
    ptpu::AppendJsonU64(&out, "threads_per_instance",
                        uint64_t(threads_per_instance));
    out += ",\"buckets\":[";
    for (size_t k = 0; k < ladder.size(); ++k) {
      if (k) out += ',';
      out += std::to_string(ladder[k]);
    }
    out += "],";
    ptpu::AppendJsonU64(&out, "n_outputs", uint64_t(n_outputs));
    out += ",\"inputs\":[";
    for (size_t i = 0; i < sig.size(); ++i) {
      if (i) out += ',';
      out += "{\"name\":\"" + ptpu::JsonEscape(sig[i].name) + "\",";
      ptpu::AppendJsonU64(&out, "dtype", uint64_t(sig[i].dtype));
      out += ",\"tail_dims\":[";
      for (size_t k = 0; k < sig[i].tail.size(); ++k) {
        if (k) out += ',';
        out += std::to_string(sig[i].tail[k]);
      }
      out += "]}";
    }
    out += "]";
    if (dec_pred) {
      out += ",\"decode\":{";
      ptpu::AppendJsonU64(&out, "batch", uint64_t(dec_batch));
      out += ',';
      ptpu::AppendJsonU64(&out, "context", uint64_t(dec_ctx));
      out += ',';
      ptpu::AppendJsonU64(&out, "kv_sessions", uint64_t(kv_sessions));
      out += ',';
      ptpu::AppendJsonU64(&out, "logit_elems",
                          uint64_t(dec_logit_elems));
      out += ',';
      ptpu::AppendJsonU64(&out, "paged", kv_paged ? 1 : 0);
      out += ',';
      ptpu::AppendJsonU64(&out, "direct",
                          uint64_t(ptpu_predictor_kv_direct(dec_pred)));
      out += ',';
      ptpu::AppendJsonU64(&out, "prefill_chunk",
                          uint64_t(prefill_chunk));
      out += ",\"step_buckets\":[";
      for (size_t k = 0; k < dec_ladder.size(); ++k) {
        if (k) out += ',';
        out += std::to_string(dec_ladder[k]);
      }
      out += "]";
      if (spec_k > 0) {
        out += ",\"spec\":{";
        ptpu::AppendJsonU64(&out, "k", uint64_t(spec_k));
        out += ',';
        ptpu::AppendJsonU64(&out, "verify_width", uint64_t(ver_width));
        out += ',';
        ptpu::AppendJsonU64(&out, "draft_context",
                            uint64_t(draft_ctx));
        out += ",\"verify_buckets\":[";
        for (size_t k = 0; k < ver_ladder.size(); ++k) {
          if (k) out += ',';
          out += std::to_string(ver_ladder[k]);
        }
        out += "],\"draft_buckets\":[";
        for (size_t k = 0; k < draft_ladder.size(); ++k) {
          if (k) out += ',';
          out += std::to_string(draft_ladder[k]);
        }
        out += "]}";
      }
      if (kv_pool) {
        out += ",\"pool\":";
        out += ptpu_kvpool_stats_json(kv_pool);
      }
      out += '}';
    }
    out += "}";
    meta_json = std::move(out);
  }

  // ---------------------------------------------------- telemetry
  // HTTP endpoints on the second listener (same event threads): the
  // serving control plane's health/metrics/trace surface (shared
  // routes — csrc/ptpu_net.cc TelemetryHttp).
  ptpu::net::HttpReply HandleHttp(const std::string& target) {
    // serving-only route: the shadow-diff snapshot (the shared
    // TelemetryHttp table serves everything else, /capturez included)
    const std::string path = target.substr(0, target.find('?'));
    if (path == "/shadowz") {
      ptpu::net::HttpReply rep;
      rep.content_type = "application/json";
      rep.body = ShadowJson();
      rep.body += '\n';
      return rep;
    }
    if (path == "/invarz") {
      // conservation-law report over a fresh snapshot (ISSUE 20).
      // Served any time; `==` laws are authoritative only at quiesce
      // (ptpu_invar.h) — mid-flight requests legitimately skew them.
      ptpu::net::HttpReply rep;
      rep.content_type = "application/json";
      rep.body = ptpu::invar::CheckJson(StatsJson(), "serving");
      rep.body += '\n';
      return rep;
    }
    return ptpu::net::TelemetryHttp(
        target, [this] { return StatsJson(); }, "ptpu_serving",
        draining.load(std::memory_order_relaxed) ||
            stop.load(std::memory_order_relaxed));
  }

  // Stop the framed listener + flip /healthz to "draining" (the
  // take-me-out-of-the-LB half of a zero-downtime restart): existing
  // connections and everything queued still answer; Stop() finishes.
  void DrainBegin() {
    if (draining.exchange(true)) return;
    if (net_srv) net_srv->StopAccepting();
  }

  // ------------------------------------------------------ batch run
  // Reply-frame header after the 4-byte length slot: [ver][tag], plus
  // the echoed trace id for a traced (v2) request. Returns the offset
  // where the v1 body begins.
  static size_t RepHdr(std::vector<uint8_t>& f, uint8_t tag,
                       uint64_t echo_tid) {
    f[4] = echo_tid ? kSvWireVersionTraced : kSvWireVersion;
    f[5] = tag;
    if (echo_tid) {
      ptpu::PutU64(f.data() + 6, echo_tid);
      return 6 + ptpu::trace::kTraceExt;
    }
    return 6;
  }

  void SendErrFrameRaw(const ptpu::net::ConnPtr& conn, uint64_t id,
                       const std::string& msg) {
    std::vector<uint8_t> f = conn->AcquireBuf();
    f.resize(4 + 2 + 8 + 4 + msg.size());
    f[4] = kSvWireVersion;
    f[5] = kTagInferErr;
    std::memcpy(f.data() + 6, &id, 8);
    PutU32(f.data() + 14, uint32_t(msg.size()));
    std::memcpy(f.data() + 18, msg.data(), msg.size());
    stats.bytes_out.Add(f.size());
    conn->SendPayload(std::move(f));
  }

  // ERR frames answering INFER requests: the req_balance error term
  // (see csrc/ptpu_invar.h — requests == replies + req_errors)
  void SendErrFrame(const ptpu::net::ConnPtr& conn, uint64_t id,
                    const std::string& msg) {
    stats.err_frames.Add(1);
    stats.req_errors.Add(1);
    SendErrFrameRaw(conn, id, msg);
  }

  // ERR frames answering decode/meta ops (never counted in
  // stats.requests): bump op_errors so req_balance stays exact and
  // err_split (err_frames == req_errors + op_errors) stays total
  void SendOpErrFrame(const ptpu::net::ConnPtr& conn, uint64_t id,
                      const std::string& msg) {
    stats.err_frames.Add(1);
    stats.op_errors.Add(1);
    SendErrFrameRaw(conn, id, msg);
  }

  void RunBatch(int instance, std::vector<SvRequest>& batch) {
    SvInstance& inst = *insts[size_t(instance)];
    /* One-time worker placement: each batcher worker serves exactly
     * one instance index, so the first batch pins the worker thread
     * to the instance's node (no-op when the topology probe is off —
     * inst.node == -1). */
    static thread_local int bound_node = -2;
    if (bound_node != inst.node) {
      ptpu::topo::BindCurrentThreadToNode(inst.node);
      bound_node = inst.node;
    }
    SvCpuScope cpu(&stats.cpu_us);
    // trace stamps: queue wait ended here; batch id keys the shared
    // batch-side spans of every co-batched request
    const int64_t t_deq = ptpu::NowUs();
    const uint64_t bid =
        batch_seq.fetch_add(1, std::memory_order_relaxed) + 1;
    int64_t rows = 0;
    for (const auto& r : batch) rows += r.rows;
    // smallest bucket that fits; pad rows up to it (zero rows — their
    // outputs are computed and discarded, which keeps the run on the
    // bucket's pre-planned arena instead of falling off-plan)
    int64_t bucket = ladder.back();
    for (int64_t b : ladder)
      if (b >= rows) {
        bucket = b;
        break;
      }
    if (bucket != rows) stats.bucket_miss.Add(1);
    PTPU_Predictor* p = inst.buckets[bucket];

    char err[512] = {0};
    const auto fail_all = [&](const std::string& msg) {
      for (auto& r : batch) {
        SendErrFrame(r.conn, r.id, msg);
        r.conn->NotePending(-1);  // pairs the enqueue-time +1
      }
    };

    /* Gather batch inputs STRAIGHT from the pinned wire buffers into
     * the predictor's input storage (ISSUE 17a): input_alloc hands
     * back the batch tensor's bytes, so one pass replaces the old
     * wire->SvInput copy + SvInput->stage copy + stage->tensor copy.
     * i32 wire payloads widen into the predictor's int64 storage as
     * they land — exactly the widening set_input_i32 performed on its
     * own copy. */
    // the shadow mirror (end of this function) re-reads the gathered
    // batch straight out of the primary's input storage — valid until
    // this worker's NEXT input_alloc on p, i.e. its next batch
    std::vector<void*> in_ptrs;
    in_ptrs.reserve(sig.size());
    for (size_t i = 0; i < sig.size(); ++i) {
      std::vector<int64_t> dims;
      dims.push_back(bucket);
      dims.insert(dims.end(), sig[i].tail.begin(), sig[i].tail.end());
      void* dst = ptpu_predictor_input_alloc(
          p, sig[i].name.c_str(), sig[i].dtype, dims.data(),
          int(dims.size()), err, sizeof(err));
      if (!dst) return fail_all(std::string("input_alloc: ") + err);
      in_ptrs.push_back(dst);
      const size_t total_el = size_t(bucket) * size_t(sig[i].row_elems);
      if (sig[i].dtype == SV_I32) {
        int64_t* d = static_cast<int64_t*>(dst);
        size_t el = 0;
        for (const auto& r : batch) {
          const uint8_t* src = r.inputs[i].bytes();
          const size_t ne = r.inputs[i].nbytes() / 4;
          for (size_t k = 0; k < ne; ++k)
            d[el++] = int64_t(int32_t(GetU32(src + 4 * k)));
        }
        for (; el < total_el; ++el) d[el] = 0;  // pad rows
      } else {
        uint8_t* d = static_cast<uint8_t*>(dst);
        const size_t esz = size_t(sv_dtype_size(sig[i].dtype));
        size_t off = 0;
        for (const auto& r : batch) {
          std::memcpy(d + off, r.inputs[i].bytes(),
                      r.inputs[i].nbytes());
          off += r.inputs[i].nbytes();
        }
        const size_t need = total_el * esz;
        if (off < need) std::memset(d + off, 0, need - off);
      }
    }

    const int64_t t0 = ptpu::NowUs();
    if (ptpu_predictor_run(p, err, sizeof(err)) != 0)
      return fail_all(std::string("run: ") + err);
    const int64_t t1 = ptpu::NowUs();
    stats.run_us.Observe(uint64_t(t1 - t0));

    /* De-mux row-wise, FIFO: request k gets rows [row_off, row_off +
     * rows_k) of every output — but the rows are never copied into
     * reply frames anymore (ISSUE 17b). The run's outputs detach into
     * a refcounted pin shared by every reply of this batch; each
     * reply is a scatter frame whose payload segments point straight
     * into the pinned storage, released when the net core flushes (or
     * abandons) the last byte. */
    auto rp = std::make_shared<SvReplyPin>();
    rp->opin = ptpu_predictor_outputs_detach(p);
    if (!rp->opin || ptpu_outputs_pin_count(rp->opin) != n_outputs)
      return fail_all("run lost its outputs");
    struct OutView {
      const float* data;
      std::vector<int64_t> dims;
      int64_t row_elems;
    };
    std::vector<OutView> outs;
    for (int o = 0; o < n_outputs; ++o) {
      OutView v;
      const int nd = ptpu_outputs_pin_ndim(rp->opin, o);
      const int64_t* od = ptpu_outputs_pin_dims(rp->opin, o);
      v.data = ptpu_outputs_pin_data(rp->opin, o);
      if (nd < 1 || !od || !v.data || od[0] != bucket)
        return fail_all("output " + std::to_string(o) +
                        " lost the batch axis");
      v.dims.assign(od, od + nd);
      v.row_elems = 1;
      for (int k = 1; k < nd; ++k) v.row_elems *= od[k];
      outs.push_back(std::move(v));
    }

    int64_t row_off = 0;
    for (auto& r : batch) {
      /* Scatter frame: the owned head carries [len][ver][tag](+trace
       * id echo)[id][u16 n_outputs] plus output 0's [ndim][dims]
       * metadata (contiguous with the header on the wire); output
       * 0's raw rows are a pinned segment. Outputs past the first
       * interleave [ndim][dims] metadata — small pin-owned chunks —
       * with their pinned payload segments, preserving the exact v1
       * byte layout. */
      std::vector<uint8_t> head = r.conn->AcquireBuf();
      head.resize(4 + 2 + (r.wire_tid ? 8 : 0) + 8 + 2 + 1 +
                  outs[0].dims.size() * 8);
      const size_t ho = RepHdr(head, kTagInferRep, r.wire_tid);
      std::memcpy(head.data() + ho, &r.id, 8);
      const uint16_t no16 = uint16_t(n_outputs);
      std::memcpy(head.data() + ho + 8, &no16, 2);
      size_t sent = head.size();
      std::vector<ptpu::net::OutSeg> segs;
      segs.reserve(size_t(n_outputs) * 2);
      size_t moff = ho + 10;  // metadata cursor (head for output 0)
      for (int o = 0; o < n_outputs; ++o) {
        const OutView& v = outs[size_t(o)];
        uint8_t* mb;
        if (o == 0) {
          mb = head.data() + moff;
        } else {
          rp->meta.emplace_back(1 + v.dims.size() * 8);
          mb = rp->meta.back().data();
          segs.push_back({mb, rp->meta.back().size()});
          sent += rp->meta.back().size();
        }
        mb[0] = uint8_t(v.dims.size());
        const int64_t d0 = r.rows;
        std::memcpy(mb + 1, &d0, 8);
        for (size_t k = 1; k < v.dims.size(); ++k)
          std::memcpy(mb + 1 + 8 * k, &v.dims[k], 8);
        const size_t nb = size_t(r.rows) * size_t(v.row_elems) * 4;
        segs.push_back(
            {reinterpret_cast<const uint8_t*>(v.data +
                                              row_off * v.row_elems),
             nb});
        sent += nb;
      }
      row_off += r.rows;
      // count BEFORE the send: SendPayload hands the frame to the
      // event loop, so a client can read the reply and query stats
      // in-process before this worker resumes — the counter must
      // already cover every reply a client has seen. A dead-conn
      // send failure overcounts by one, but that client observes
      // nothing, so the exactness contract (stats selftests) holds.
      stats.replies.Add(1);
      stats.bytes_out.Add(sent);
      if (r.conn->SendScatter(std::move(head), std::move(segs), rp,
                              r.trace_id, r.id)) {
        const int64_t t_rep = ptpu::NowUs();
        stats.e2e_us.Observe(uint64_t(t_rep - r.t_enq_us));
        if (r.trace_id) {
          // the INFER lifecycle: read -> queue -> batch -> run (the
          // net core adds net.flush when the reply hits the wire)
          auto& tr = ptpu::trace::Global();
          const uint64_t cid = r.conn->id();
          tr.Record(r.trace_id, ptpu::trace::kRead, r.t_read_us,
                    r.t_enq_us, cid, r.id);
          tr.Record(r.trace_id, ptpu::trace::kQueue, r.t_enq_us, t_deq,
                    cid, bid);
          tr.Record(r.trace_id, ptpu::trace::kBatch, t_deq, t0, cid,
                    bid);
          tr.Record(r.trace_id, ptpu::trace::kRun, t0, t1, cid, bid);
        }
        if (ptpu::trace::Global().SlowEligible(t_rep - r.t_read_us)) {
          const ptpu::trace::SpanRec sp[4] = {
              {ptpu::trace::kRead, r.t_read_us, r.t_enq_us},
              {ptpu::trace::kQueue, r.t_enq_us, t_deq},
              {ptpu::trace::kBatch, t_deq, t0},
              {ptpu::trace::kRun, t0, t1}};
          ptpu::trace::Global().RecordSlow(r.trace_id, r.conn->id(),
                                           r.id, t_rep - r.t_read_us,
                                           sp, 4);
        }
      }
      r.conn->NotePending(-1);  // pairs the enqueue-time +1
    }

    /* ---- shadow mirror (production drills): re-run 1-in-N batches
     * on the shadow artifact and diff outputs + latency. Runs AFTER
     * every primary reply is queued — mirroring adds zero latency to
     * the answers clients see; the primary outputs stay comparable
     * through rp (the replies' pin), the inputs through in_ptrs. */
    if (!shadow_buckets.empty() &&
        shadow_ctr_.fetch_add(1, std::memory_order_relaxed) %
                uint64_t(shadow_sample) ==
            0) {
      ptpu::MutexLock sl(shadow_mu_);
      PTPU_Predictor* sp = shadow_buckets[bucket];
      bool fed = true;
      for (size_t i = 0; i < sig.size(); ++i) {
        std::vector<int64_t> dims;
        dims.push_back(bucket);
        dims.insert(dims.end(), sig[i].tail.begin(),
                    sig[i].tail.end());
        void* sdst = ptpu_predictor_input_alloc(
            sp, sig[i].name.c_str(), sig[i].dtype, dims.data(),
            int(dims.size()), err, sizeof(err));
        if (!sdst) {
          sstats.run_errors.Add(1);
          fed = false;
          break;
        }
        // i32 wire inputs widened into int64 storage at gather; the
        // primary's storage bytes ARE the batch, padding included
        const size_t esz = sig[i].dtype == SV_I32
                               ? 8
                               : size_t(sv_dtype_size(sig[i].dtype));
        std::memcpy(sdst, in_ptrs[i],
                    size_t(bucket) * size_t(sig[i].row_elems) * esz);
      }
      if (fed) {
        const int64_t s0 = ptpu::NowUs();
        if (ptpu_predictor_run(sp, err, sizeof(err)) != 0) {
          sstats.run_errors.Add(1);
        } else {
          const int64_t s1 = ptpu::NowUs();
          sstats.batches.Add(1);
          sstats.requests.Add(uint64_t(batch.size()));
          sstats.primary_run_us.Add(uint64_t(t1 - t0));
          sstats.shadow_run_us.Add(uint64_t(s1 - s0));
          double maxd = 0;
          bool shape_mismatch = false;
          for (int o = 0; o < n_outputs; ++o) {
            const OutView& v = outs[size_t(o)];
            const int nd = ptpu_predictor_output_ndim(sp, o);
            const int64_t* od = ptpu_predictor_output_dims(sp, o);
            const float* sd = ptpu_predictor_output_data(sp, o);
            if (nd != int(v.dims.size()) || !od || !sd) {
              shape_mismatch = true;
              continue;
            }
            bool dims_ok = true;
            for (int k = 0; k < nd; ++k)
              dims_ok = dims_ok && od[k] == v.dims[size_t(k)];
            if (!dims_ok) {
              shape_mismatch = true;
              continue;
            }
            // real rows only — the padded bucket tail is computed
            // garbage on BOTH models and must not pollute the diff
            const size_t ne = size_t(rows) * size_t(v.row_elems);
            for (size_t k = 0; k < ne; ++k) {
              const double d =
                  std::fabs(double(sd[k]) - double(v.data[k]));
              if (d > maxd) maxd = d;
            }
          }
          // worst |Δ| in 1e-9 units (u64 keeps /metrics walkable);
          // CAS-max races only with other mirrored batches
          const uint64_t nv =
              uint64_t(std::min(maxd * 1e9, 1e18));
          uint64_t cur =
              sstats.max_abs_diff_e9.load(std::memory_order_relaxed);
          while (nv > cur &&
                 !sstats.max_abs_diff_e9.compare_exchange_weak(
                     cur, nv, std::memory_order_relaxed)) {
          }
          if (shape_mismatch || maxd > shadow_tol)
            sstats.mismatched_batches.Add(1);
        }
      }
    }
  }

  // ------------------------------------------------- decode plane
  bool DecodeOpen(const ptpu::net::ConnPtr& conn, uint64_t* sess,
                  std::string* why) {
    ptpu::MutexLock kl(kv_mu_);
    ptpu::MutexLock l(sess_mu_);
    return OpenSlotLocked(conn, sess, why);
  }

  /* hibernate a live wire session into the spill tier (kv_mu_ +
   * sess_mu_ held, ISSUE 19). On success the session's pool slot(s)
   * are freed and ws.hib / ws.hib_draft hold the opaque pool records;
   * SpecState (rng, committed history) stays resident — only pool
   * state tiers out. Returns true iff the target pool slot was freed
   * (in the pathological draft-rollback-failure case by dropping the
   * session, counted as an eviction). */
  bool HibernateLocked(uint64_t id, WireSession& ws) {
    if (ws.slot < 0 || !kv_pool) return false;
    if (prefills_.count(id)) return false;  // mid-prefill: slot is hot
    char err[256] = {0};
    const int64_t need = ptpu_kvpool_hibernate(kv_pool, ws.slot,
                                               nullptr, 0, err,
                                               sizeof(err));
    if (need < 0) return false;
    std::vector<uint8_t> rec(static_cast<size_t>(need));
    const int64_t got = ptpu_kvpool_hibernate(
        kv_pool, ws.slot, rec.data(), need, err, sizeof(err));
    if (got < 0) {
      if (std::strstr(err, "spill exhausted"))
        dstats.spill_exhausted.Add(1);
      return false;
    }
    rec.resize(size_t(got));
    if (ws.spec && ws.spec->draft_slot >= 0 && draft_pool) {
      // spec-twin linkage: the draft session hibernates alongside the
      // target so a later restore resumes rounds mid-history
      char derr[256] = {0};
      const int64_t dneed =
          ptpu_kvpool_hibernate(draft_pool, ws.spec->draft_slot,
                                nullptr, 0, derr, sizeof(derr));
      std::vector<uint8_t> drec;
      int64_t dgot = -1;
      if (dneed >= 0) {
        drec.resize(size_t(dneed));
        dgot = ptpu_kvpool_hibernate(draft_pool, ws.spec->draft_slot,
                                     drec.data(), dneed, derr,
                                     sizeof(derr));
      }
      if (dgot < 0) {
        if (std::strstr(derr, "spill exhausted"))
          dstats.spill_exhausted.Add(1);
        // roll the target back to resident; if even that fails the
        // session is unrecoverable — drop the record (tombstone)
        const int back =
            ptpu_kvpool_restore(kv_pool, rec.data(),
                                int64_t(rec.size()), err, sizeof(err));
        if (back >= 0) {
          ws.slot = back;
        } else {
          ptpu_kvpool_hibernate_drop(kv_pool, rec.data(),
                                     int64_t(rec.size()));
          ws.slot = -1;
          CloseSpecLocked(ws);
          dstats.evictions.Add(1);
          return true;  // the slot IS free, just not by hibernation
        }
        return false;
      }
      drec.resize(size_t(dgot));
      ws.hib_draft = std::move(drec);
      ws.spec->draft_slot = -1;
    }
    ws.hib = std::move(rec);
    ws.slot = -1;
    dstats.hibernates.Add(1);
    return true;
  }

  // kv_mu_ + sess_mu_ held: make room for one more pool session by
  // hibernating (spill tier attached) or tombstone-evicting the
  // least-recently-stepped live wire session
  bool EvictOneLocked(std::string* why) {
    uint64_t victim = 0, oldest = UINT64_MAX;
    bool found = false;
    for (const auto& kv : sessions_)
      if (kv.second.slot >= 0 && !kv.second.pinned &&
          kv.second.last_us < oldest) {
        oldest = kv.second.last_us;
        victim = kv.first;
        found = true;
      }
    if (!found) {
      *why = "no KV session slots";
      return false;
    }
    // tiering on: hibernate instead of evicting — the session
    // survives with its pool state in the spill tier
    if (!sv_spill_path.empty() &&
        HibernateLocked(victim, sessions_[victim]))
      return true;
    ptpu_predictor_kv_close(dec_pred, sessions_[victim].slot);
    sessions_[victim].slot = -1;
    CloseSpecLocked(sessions_[victim]);
    dstats.evictions.Add(1);
    // an evicted session may still be mid-prefill: its OPEN2 must
    // answer NOW (queued prefill steps drop at the tombstone), or
    // the client waits forever on a session that no longer exists
    auto jit = prefills_.find(victim);
    if (jit != prefills_.end()) {
      SendOpErrFrame(jit->second->conn, jit->second->rid,
                   "decode session evicted");
      jit->second->conn->NotePending(-1);
      prefills_.erase(jit);
    }
    return true;
  }

  // kv_mu_ + sess_mu_ held; allocates a predictor/pool session with
  // LRU eviction of the least-recently-stepped live wire session
  bool OpenSlotLocked(const ptpu::net::ConnPtr& conn, uint64_t* sess,
                      std::string* why) {
    int slot = ptpu_predictor_kv_open(dec_pred);
    if (slot < 0) {
      // every KV slot busy: hibernate or evict the
      // least-recently-stepped live session
      if (!EvictOneLocked(why)) return false;
      slot = ptpu_predictor_kv_open(dec_pred);
      if (slot < 0) {
        *why = "no KV session slots";
        return false;
      }
    }
    // bound tombstone growth: drop the oldest evicted entries once
    // they outnumber the live slots 4:1. Hibernated sessions (slot
    // -1 but a live spill record) are NOT tombstones — holding many
    // of them at bounded RSS is the point of the tier.
    size_t tombs = 0;
    for (const auto& kv : sessions_)
      if (kv.second.slot < 0 && kv.second.hib.empty()) ++tombs;
    for (auto it = sessions_.begin();
         tombs > size_t(4 * kv_sessions) && it != sessions_.end();) {
      if (it->second.slot < 0 && it->second.hib.empty()) {
        it = sessions_.erase(it);
        --tombs;
      } else {
        ++it;
      }
    }
    const uint64_t id = next_session_++;
    WireSession ws;
    ws.slot = slot;
    ws.last_us = uint64_t(ptpu::NowUs());
    ws.owner = conn.get();
    sessions_[id] = std::move(ws);
    dstats.opens.Add(1);
    *sess = id;
    return true;
  }

  /* restore a hibernated wire session's pool state (kv_mu_ +
   * sess_mu_ held, ISSUE 19). Soft failures ("kv pool exhausted",
   * "kv spill exhausted", full tables) set *why and leave the session
   * hibernated — the caller answers a retryable row error, exactly
   * like pool_exhausted backpressure. */
  bool RestoreLocked(WireSession& ws, std::string* why) {
    const int64_t t0 = ptpu::NowUs();
    char err[256] = {0};
    int slot = ptpu_kvpool_restore(kv_pool, ws.hib.data(),
                                   int64_t(ws.hib.size()), err,
                                   sizeof(err));
    if (slot == -1) {
      // pool session table full: free one resident slot, retry once
      std::string ewhy;
      if (EvictOneLocked(&ewhy))
        slot = ptpu_kvpool_restore(kv_pool, ws.hib.data(),
                                   int64_t(ws.hib.size()), err,
                                   sizeof(err));
    }
    if (slot < 0) {
      if (std::strstr(err, "kv pool exhausted"))
        dstats.pool_exhausted.Add(1);
      *why = slot == -1 ? "no KV session slots"
                        : std::string("restore: ") + err;
      return false;
    }
    if (ws.spec && !ws.hib_draft.empty()) {
      char derr[256] = {0};
      const int ds = ptpu_kvpool_restore(
          draft_pool, ws.hib_draft.data(),
          int64_t(ws.hib_draft.size()), derr, sizeof(derr));
      if (ds < 0) {
        if (std::strstr(derr, "kv pool exhausted"))
          dstats.pool_exhausted.Add(1);
        // tier the freshly-restored target back out so the session
        // stays whole; the step retries later
        const int64_t need = ptpu_kvpool_hibernate(
            kv_pool, slot, nullptr, 0, err, sizeof(err));
        bool back = false;
        if (need >= 0) {
          std::vector<uint8_t> rec(static_cast<size_t>(need));
          const int64_t got = ptpu_kvpool_hibernate(
              kv_pool, slot, rec.data(), need, err, sizeof(err));
          if (got >= 0) {
            rec.resize(size_t(got));
            ws.hib = std::move(rec);
            back = true;
          }
        }
        if (!back) {
          // unrecoverable: drop both planes (tombstone)
          ptpu_predictor_kv_close(dec_pred, slot);
          ptpu_kvpool_hibernate_drop(draft_pool, ws.hib_draft.data(),
                                     int64_t(ws.hib_draft.size()));
          ws.hib.clear();
          ws.hib_draft.clear();
          CloseSpecLocked(ws);
          dstats.evictions.Add(1);
          *why = "decode session evicted";
          return false;
        }
        *why = ds == -1 ? "no draft KV session slots"
                        : std::string("restore: ") + derr;
        return false;
      }
      ws.spec->draft_slot = ds;
      ws.hib_draft.clear();
    }
    ws.hib.clear();
    ws.slot = slot;
    ws.last_us = uint64_t(ptpu::NowUs());
    dstats.restores.Add(1);
    dstats.restore_us.Observe(uint64_t(ptpu::NowUs() - t0));
    return true;
  }

  // kv_mu_ + sess_mu_ held: release a departing session's spill-tier
  // state (no-op for resident/tombstone sessions)
  void DropHibLocked(WireSession& ws) {
    if (!ws.hib.empty() && kv_pool)
      ptpu_kvpool_hibernate_drop(kv_pool, ws.hib.data(),
                                 int64_t(ws.hib.size()));
    if (!ws.hib_draft.empty() && draft_pool)
      ptpu_kvpool_hibernate_drop(draft_pool, ws.hib_draft.data(),
                                 int64_t(ws.hib_draft.size()));
    ws.hib.clear();
    ws.hib_draft.clear();
  }

  bool DecodeClose(uint64_t sess, std::string* why) {
    ptpu::MutexLock kl(kv_mu_);
    ptpu::MutexLock l(sess_mu_);
    auto it = sessions_.find(sess);
    if (it == sessions_.end()) {
      *why = "unknown decode session";
      return false;
    }
    if (it->second.slot >= 0)
      ptpu_predictor_kv_close(dec_pred, it->second.slot);
    CloseSpecLocked(it->second);
    // tombstones (slot -1, no hibernation record) already exited the
    // session_balance ledger as evictions: closing one later must
    // not count a second exit
    const bool counted_exit =
        it->second.slot >= 0 || !it->second.hib.empty();
    DropHibLocked(it->second);
    sessions_.erase(it);
    // a prefilling session closed out from under its job (only
    // reachable via a racing second connection guessing the id —
    // clients learn the id from OPEN_REP): drop the job, balance the
    // OPEN2 pending mark, leave the open frame unanswered
    auto jit = prefills_.find(sess);
    if (jit != prefills_.end()) {
      jit->second->conn->NotePending(-1);
      prefills_.erase(jit);
    }
    if (counted_exit) dstats.closes.Add(1);
    return true;
  }

  void DecodeConnClosed(const void* conn) {
    if (!dec_pred) return;
    {
      // fast path for the common case — a closing connection that
      // never opened a decode session must not wait out a running
      // decode batch on kv_mu_ (that would stall its whole event loop)
      ptpu::MutexLock l(sess_mu_);
      bool owns = false;
      for (const auto& kv : sessions_)
        if (kv.second.owner == conn) {
          owns = true;
          break;
        }
      if (!owns) return;
    }
    ptpu::MutexLock kl(kv_mu_);
    ptpu::MutexLock l(sess_mu_);
    for (auto it = sessions_.begin(); it != sessions_.end();) {
      if (it->second.owner == conn) {
        // a live or hibernated session dying with its conn IS a
        // close — the session_balance ledger (csrc/ptpu_invar.h)
        // counts every exit exactly once. Tombstones already exited
        // as evictions and must not count twice.
        if (it->second.slot >= 0 || !it->second.hib.empty())
          dstats.closes.Add(1);
        if (it->second.slot >= 0)
          ptpu_predictor_kv_close(dec_pred, it->second.slot);
        CloseSpecLocked(it->second);
        DropHibLocked(it->second);
        prefills_.erase(it->first);  // conn is gone: no reply owed
        it = sessions_.erase(it);
      } else {
        ++it;
      }
    }
  }

  /* ---- chunked prompt prefill (ISSUE r12) ----
   * OPEN2 turns a prompt into server-internal decode steps admitted
   * at most `prefill_chunk` at a time: the steps ride the SAME
   * micro-batcher FIFO as everyone's decode steps, so a 1,000-token
   * prompt interleaves with running sessions instead of stalling
   * them. Shared prefix pages are adopted from the pool's prompt
   * cache before any compute; the full prompt pages publish back into
   * the cache when prefill completes. */
  void DecodeOpen2(const ptpu::net::ConnPtr& conn, uint64_t rid,
                   uint64_t wire_tid, std::vector<int64_t>&& toks) {
    const int64_t ntok = int64_t(toks.size());
    uint64_t sess = 0;
    int64_t adopted = 0;
    {
      std::string why;
      ptpu::MutexLock kl(kv_mu_);
      ptpu::MutexLock l(sess_mu_);
      if (!OpenSlotLocked(conn, &sess, &why)) {
        SendOpErrFrame(conn, rid, why);
        return;
      }
      if (kv_pool)
        adopted = ptpu_kvpool_adopt(kv_pool, sessions_[sess].slot,
                                    toks.data(), ntok);
      auto* job = new PrefillJob;
      job->sess = sess;
      job->rid = rid;
      job->conn = conn;
      job->wire_tid = wire_tid;
      job->tokens = std::move(toks);
      job->next = adopted;
      job->done = adopted;
      job->adopted = adopted;
      prefills_[sess].reset(job);
      dstats.prefills.Add(1);
      dstats.prefill_adopted.Add(uint64_t(adopted));
      dstats.prefill_tokens.Add(uint64_t(ntok - adopted));
    }
    conn->NotePending(1);  // paired by OPEN_REP / the job's error
    PrefillAdmit(sess);
  }

  bool DecodeFork(const ptpu::net::ConnPtr& conn, uint64_t src,
                  uint64_t* nsess, std::string* why) {
    ptpu::MutexLock kl(kv_mu_);
    ptpu::MutexLock l(sess_mu_);
    if (!kv_pool) {
      *why = "fork needs the paged KV engine (PTPU_KV_PAGED)";
      return false;
    }
    auto it = sessions_.find(src);
    if (it == sessions_.end()) {
      *why = "unknown decode session";
      return false;
    }
    if (it->second.slot < 0) {
      // hibernated source: restore first, then fork (ISSUE 19)
      if (it->second.hib.empty()) {
        *why = "decode session evicted";
        return false;
      }
      if (!RestoreLocked(it->second, why)) return false;
    }
    if (prefills_.count(src)) {
      *why = "session is still prefilling";
      return false;
    }
    if (it->second.spec) {
      // a fork would need a draft twin + sampler-state clone; not a
      // supported shape yet
      *why = "cannot fork a speculative session";
      return false;
    }
    const int ns = ptpu_kvpool_fork(kv_pool, it->second.slot);
    if (ns < 0) {
      *why = "no KV session slots";
      return false;
    }
    const uint64_t id = next_session_++;
    WireSession ws;
    ws.slot = ns;
    ws.last_us = uint64_t(ptpu::NowUs());
    ws.owner = conn.get();
    sessions_[id] = std::move(ws);
    dstats.forks.Add(1);
    dstats.opens.Add(1);
    *nsess = id;
    return true;
  }

  // close a session's draft-side state (sess_mu_ held); safe when the
  // session never was speculative
  void CloseSpecLocked(WireSession& ws) {
    if (ws.spec && ws.spec->draft_slot >= 0 && draft_pool)
      ptpu_kvpool_close(draft_pool, ws.spec->draft_slot);
    ws.spec.reset();
  }

  // pick the next committed token from target logits: argmax (greedy)
  // or one softmax draw (sampling) — exactly the primitive a
  // non-speculative sampler applies to the same logits
  int64_t SpecPick(SpecState& st, const float* lg) {
    if (!st.sample) return spec_argmax(lg, dec_logit_elems);
    std::vector<float> p(static_cast<size_t>(dec_logit_elems));
    spec_softmax(lg, dec_logit_elems, p.data());
    return spec_sample(p.data(), dec_logit_elems, 1.0,
                       spec_u01(&st.rng));
  }

  void SendSpecRep(const ptpu::net::ConnPtr& conn, uint64_t rid,
                   uint64_t sess, uint64_t wire_tid, uint32_t accepted,
                   const int64_t* toks, uint32_t n) {
    std::vector<uint8_t> f = conn->AcquireBuf();
    f.resize(4 + 2 + (wire_tid ? 8 : 0) + 8 + 8 + 4 + 4 +
             8ull * n);
    const size_t ho = RepHdr(f, kTagDecodeSpecRep, wire_tid);
    ptpu::PutU64(f.data() + ho, rid);
    ptpu::PutU64(f.data() + ho + 8, sess);
    PutU32(f.data() + ho + 16, accepted);
    PutU32(f.data() + ho + 20, n);
    for (uint32_t k = 0; k < n; ++k)
      ptpu::PutI64(f.data() + ho + 24 + 8 * size_t(k),
                   toks[size_t(k)]);
    stats.bytes_out.Add(f.size());
    conn->SendPayload(std::move(f));
  }

  /* SPEC_OPEN: open a target session + its draft twin, adopt shared
   * prefix pages in BOTH pools, then prefill the target prompt through
   * the existing chunked machinery (job->spec routes completion to a
   * SPEC_REP carrying the first generated token). The draft session is
   * NOT prefilled here — rounds catch it up chunk-wise, so a long
   * prompt never stalls running sessions on the draft plane either. */
  void DecodeSpecOpen(const ptpu::net::ConnPtr& conn, uint64_t rid,
                      uint64_t wire_tid, uint32_t flags, uint64_t seed,
                      std::vector<int64_t>&& toks) {
    const int64_t ntok = int64_t(toks.size());
    uint64_t sess = 0;
    {
      std::string why;
      ptpu::MutexLock kl(kv_mu_);
      ptpu::MutexLock l(sess_mu_);
      if (!OpenSlotLocked(conn, &sess, &why)) {
        SendOpErrFrame(conn, rid, why);
        return;
      }
      const int dslot = ptpu_kvpool_open(draft_pool);
      if (dslot < 0) {
        ptpu_predictor_kv_close(dec_pred, sessions_[sess].slot);
        sessions_.erase(sess);
        // the open above already counted: this exit balances it
        dstats.closes.Add(1);
        SendOpErrFrame(conn, rid, "no draft KV session slots");
        return;
      }
      const int64_t adopted = ptpu_kvpool_adopt(
          kv_pool, sessions_[sess].slot, toks.data(), ntok);
      auto* st = new SpecState;
      st->sample = (flags & 1u) != 0;
      st->rng = seed ? seed : 0x9e3779b97f4a7c15ull;
      st->draft_slot = dslot;
      st->committed = toks;          // the prompt; the first generated
                                     // token lands at prefill end
      st->prompt_len = ntok;
      st->draft_len = ntok <= draft_ctx
                          ? ptpu_kvpool_adopt(draft_pool, dslot,
                                              toks.data(), ntok)
                          : 0;
      sessions_[sess].spec.reset(st);
      auto* job = new PrefillJob;
      job->sess = sess;
      job->rid = rid;
      job->conn = conn;
      job->wire_tid = wire_tid;
      job->tokens = std::move(toks);
      job->next = adopted;
      job->done = adopted;
      job->adopted = adopted;
      job->spec = true;
      prefills_[sess].reset(job);
      dstats.prefills.Add(1);
      dstats.prefill_adopted.Add(uint64_t(adopted));
      dstats.prefill_tokens.Add(uint64_t(ntok - adopted));
    }
    conn->NotePending(1);  // paired by SPEC_REP / the job's error
    PrefillAdmit(sess);
  }

  // admit the next chunk of a job's prompt into the decode batcher;
  // a full queue parks the job on prefill_resume_ for the next flush
  void PrefillAdmit(uint64_t sess) {
    ptpu::MutexLock l(sess_mu_);
    auto it = prefills_.find(sess);
    if (it == prefills_.end()) return;
    PrefillJob* job = it->second.get();
    const int64_t total = int64_t(job->tokens.size());
    while (job->next < total && job->next - job->done < prefill_chunk) {
      SvRequest r;
      r.is_decode = true;
      r.is_prefill = true;
      r.id = job->rid;
      r.session = sess;
      r.token = job->tokens[size_t(job->next)];
      r.rows = 1;
      r.conn = job->conn;
      r.wire_tid = 0;
      r.trace_id = 0;
      r.t_read_us = r.t_enq_us = ptpu::NowUs();
      std::string why;
      if (!dec_batcher->enqueue(std::move(r), &why)) {
        prefill_resume_.push_back(sess);
        return;
      }
      ++job->next;
    }
  }

  void PrefillResume() {
    std::vector<uint64_t> retry;
    {
      ptpu::MutexLock l(sess_mu_);
      retry.swap(prefill_resume_);
    }
    for (uint64_t s : retry) PrefillAdmit(s);
  }

  // a prefill step errored (bad token, pool exhausted after retries):
  // answer the OPEN2 with the error, drop the job and its session
  // (kv_mu_ held — called from the decode runner)
  void PrefillRowError(uint64_t sess, const std::string& why) {
    ptpu::net::ConnPtr conn;
    uint64_t rid = 0;
    int slot = -1;
    {
      ptpu::MutexLock l(sess_mu_);
      auto it = prefills_.find(sess);
      if (it == prefills_.end()) return;
      conn = it->second->conn;
      rid = it->second->rid;
      prefills_.erase(it);
      auto sit = sessions_.find(sess);
      if (sit != sessions_.end()) {
        slot = sit->second.slot;
        CloseSpecLocked(sit->second);
        // a failed prefill exits its (live) session: balance opens
        if (sit->second.slot >= 0 || !sit->second.hib.empty())
          dstats.closes.Add(1);
        sessions_.erase(sit);
      }
    }
    if (slot >= 0) ptpu_predictor_kv_close(dec_pred, slot);
    SendOpErrFrame(conn, rid, "prefill: " + why);
    conn->NotePending(-1);
  }

  // one prefill step finished (kv_mu_ held): bookkeep, and either
  // answer OPEN_REP with the LAST prompt token's logits + publish the
  // prompt pages, or admit the next chunk once this one drains
  void PrefillRowDone(SvRequest* r, const float* lg, int64_t row) {
    ptpu::net::ConnPtr conn;
    uint64_t rid = 0, wire_tid = 0;
    int64_t adopted = 0;
    int slot = -1;
    std::vector<int64_t> toks;
    bool fin = false, admit = false, spec = false;
    int64_t first_tok = 0;
    {
      ptpu::MutexLock l(sess_mu_);
      auto it = prefills_.find(r->session);
      if (it == prefills_.end()) return;
      PrefillJob* job = it->second.get();
      ++job->done;
      if (job->done >= int64_t(job->tokens.size())) {
        fin = true;
        conn = job->conn;
        rid = job->rid;
        wire_tid = job->wire_tid;
        adopted = job->adopted;
        spec = job->spec;
        toks.swap(job->tokens);
        auto sit = sessions_.find(r->session);
        slot = sit == sessions_.end() ? -1 : sit->second.slot;
        if (spec && sit != sessions_.end() && sit->second.spec) {
          // speculative open completes here: the first generated
          // token comes from the last prompt token's target logits
          SpecState& st = *sit->second.spec;
          first_tok = SpecPick(st, lg + row * dec_logit_elems);
          st.committed.push_back(first_tok);
        }
        prefills_.erase(it);
      } else if (job->next - job->done <= 0) {
        admit = true;
      }
    }
    if (!fin) {
      if (admit) PrefillAdmit(r->session);
      return;
    }
    if (kv_pool && slot >= 0)
      ptpu_kvpool_publish(kv_pool, slot, toks.data(),
                          int64_t(toks.size()));
    if (spec) {
      SendSpecRep(conn, rid, r->session, wire_tid, uint32_t(adopted),
                  &first_tok, 1);
      conn->NotePending(-1);
      return;
    }
    std::vector<uint8_t> f = conn->AcquireBuf();
    f.resize(4 + 2 + (wire_tid ? 8 : 0) + 8 + 8 + 4 + 4 +
             size_t(dec_logit_elems) * 4);
    const size_t ho = RepHdr(f, kTagDecodeOpenRep, wire_tid);
    ptpu::PutU64(f.data() + ho, rid);
    ptpu::PutU64(f.data() + ho + 8, r->session);
    PutU32(f.data() + ho + 16, uint32_t(adopted));
    PutU32(f.data() + ho + 20, uint32_t(dec_logit_elems));
    std::memcpy(f.data() + ho + 24, lg + row * dec_logit_elems,
                size_t(dec_logit_elems) * 4);
    stats.bytes_out.Add(f.size());
    conn->SendPayload(std::move(f));
    conn->NotePending(-1);
  }

  /* One decode flush. The FIFO may hold several steps of one session
   * (a pipelining client, or a prompt-prefill chunk); a session's
   * steps are ordered, so the batch splits into FIFO-prefix sub-runs
   * with unique sessions. Stalled prefill admissions retry first —
   * the batcher just drained, so there is room again. */
  void RunDecode(std::vector<SvRequest>& batch) {
    SvCpuScope cpu(&dstats.cpu_us);
    PrefillResume();
    if (spec_k > 0) SpecResume();
    const int64_t t_deq = ptpu::NowUs();
    for (auto& r : batch) r.t_deq_us = t_deq;
    /* Greedy order-preserving re-pack. The old FIFO-prefix split cut
     * a sub-run at the FIRST repeated session, so a queue holding
     * consecutive steps of few sessions (a prefill chunk, a client
     * pipelining one session) degraded to 1-row runs. Instead, scan
     * in FIFO order and place each step into the first sub-run AFTER
     * the session's previous placement with room and no step of the
     * same session — steps of one session stay ordered across runs,
     * while different sessions' chunks interleave into full rows. */
    std::vector<std::vector<SvRequest*>> runs;
    std::vector<std::set<uint64_t>> seen;
    std::map<uint64_t, size_t> next_run;
    for (auto& r : batch) {
      size_t k = 0;
      auto it = next_run.find(r.session);
      if (it != next_run.end()) k = it->second;
      for (; k < runs.size(); ++k)
        if (int64_t(runs[k].size()) < dec_batch &&
            !seen[k].count(r.session))
          break;
      if (k == runs.size()) {
        runs.emplace_back();
        seen.emplace_back();
      }
      runs[k].push_back(&r);
      seen[k].insert(r.session);
      next_run[r.session] = k + 1;
    }
    for (auto& run : runs) DecodeStepRun(run);
  }

  // smallest surviving step-batch bucket holding `rows` (the max
  // bucket otherwise); counts a miss when padding was unavoidable
  PTPU_Predictor* DecBucket(size_t rows) {
    for (int64_t b : dec_ladder)
      if (int64_t(rows) <= b) {
        if (int64_t(rows) < b) dstats.bucket_miss.Add(1);
        return dec_buckets[b];
      }
    return dec_pred;
  }

  // same selection over the spec planes' draft/verify ladders
  PTPU_Predictor* LadderBucket(
      const std::map<int64_t, PTPU_Predictor*>& buckets,
      const std::vector<int64_t>& ladder, size_t rows) {
    for (int64_t b : ladder)
      if (int64_t(rows) <= b) {
        if (int64_t(rows) < b) dstats.bucket_miss.Add(1);
        return buckets.at(b);
      }
    return buckets.rbegin()->second;
  }

  // re-enqueue spec rounds parked mid-catch-up by a full queue (the
  // batcher just drained, so there is room again)
  void SpecResume() {
    std::vector<SvRequest> retry;
    {
      ptpu::MutexLock l(sess_mu_);
      retry.swap(spec_resume_);
    }
    for (auto& r : retry) {
      std::string why;
      if (!dec_batcher->enqueue(std::move(r), &why)) {
        // enqueue moves only on success: r is intact — park again
        ptpu::MutexLock l(sess_mu_);
        spec_resume_.push_back(std::move(r));
      }
    }
  }

  /* Reply with row `row` of the just-run decode outputs. The logits
   * row rides as a pinned scatter segment pointing into the step's
   * detached outputs (`rp` — shared by every reply of the sub-run);
   * the owned head carries [len][ver][tag](+tid)[rid][sess]
   * [u32 n_logits]. run0/run1 bracket the ptpu_predictor_decode_step
   * that produced the row (the per-step decode.step trace span, keyed
   * by session). */
  void DecodeReply(SvRequest* r, const float* lg, int64_t row,
                   int64_t run0, int64_t run1,
                   const std::shared_ptr<SvReplyPin>& rp) {
    std::vector<uint8_t> f = r->conn->AcquireBuf();
    f.resize(4 + 2 + (r->wire_tid ? 8 : 0) + 8 + 8 + 4);
    const size_t ho = RepHdr(f, kTagDecodeRep, r->wire_tid);
    ptpu::PutU64(f.data() + ho, r->id);
    ptpu::PutU64(f.data() + ho + 8, r->session);
    PutU32(f.data() + ho + 16, uint32_t(dec_logit_elems));
    std::vector<ptpu::net::OutSeg> segs(1);
    segs[0].p =
        reinterpret_cast<const uint8_t*>(lg + row * dec_logit_elems);
    segs[0].n = size_t(dec_logit_elems) * 4;
    const size_t sent = f.size() + segs[0].n;
    // pre-send bump, same observable-ordering contract as the infer
    // reply path: a client holding the reply frame must see it counted
    dstats.replies.Add(1);
    stats.bytes_out.Add(sent);
    if (r->conn->SendScatter(std::move(f), std::move(segs), rp,
                             r->trace_id, r->session)) {
      const int64_t t_rep = ptpu::NowUs();
      stats.e2e_us.Observe(uint64_t(t_rep - r->t_enq_us));
      if (r->trace_id) {
        auto& tr = ptpu::trace::Global();
        const uint64_t cid = r->conn->id();
        tr.Record(r->trace_id, ptpu::trace::kRead, r->t_read_us,
                  r->t_enq_us, cid, r->id);
        tr.Record(r->trace_id, ptpu::trace::kQueue, r->t_enq_us,
                  r->t_deq_us, cid, r->session);
        tr.Record(r->trace_id, ptpu::trace::kBatch, r->t_deq_us, run0,
                  cid, r->session);
        tr.Record(r->trace_id, ptpu::trace::kDecode, run0, run1, cid,
                  r->session);
      }
      if (ptpu::trace::Global().SlowEligible(t_rep - r->t_read_us)) {
        const ptpu::trace::SpanRec sp[4] = {
            {ptpu::trace::kRead, r->t_read_us, r->t_enq_us},
            {ptpu::trace::kQueue, r->t_enq_us, r->t_deq_us},
            {ptpu::trace::kBatch, r->t_deq_us, run0},
            {ptpu::trace::kDecode, run0, run1}};
        ptpu::trace::Global().RecordSlow(r->trace_id, r->conn->id(),
                                         r->id, t_rep - r->t_read_us,
                                         sp, 4);
      }
    }
    r->conn->NotePending(-1);
  }

  // route a failed/completed row to its owner: client steps answer
  // frames directly, prefill steps update their job (kv_mu_ held)
  void StepRowError(SvRequest* r, const std::string& why) {
    if (r->is_prefill) {
      PrefillRowError(r->session, why);
      return;
    }
    SendOpErrFrame(r->conn, r->id, why);
    r->conn->NotePending(-1);
  }

  void DecodeStepRun(std::vector<SvRequest*>& run) {
    std::vector<int64_t> sids, toks;
    std::vector<SvRequest*> live, spec_rounds;
    ptpu::MutexLock kl(kv_mu_);
    {
      ptpu::MutexLock l(sess_mu_);
      for (auto* r : run) {
        auto it = sessions_.find(r->session);
        if (it != sessions_.end() && it->second.slot < 0 &&
            !it->second.hib.empty()) {
          // hibernated session (ISSUE 19): restore transparently —
          // the step below runs as if the session never left RAM.
          // Soft failures answer a retryable error (pool/spill
          // backpressure), same contract as pool_exhausted.
          std::string why;
          if (!RestoreLocked(it->second, &why)) {
            if (r->is_prefill) continue;
            SendOpErrFrame(r->conn, r->id, why);
            r->conn->NotePending(-1);
            continue;
          }
        }
        if (it == sessions_.end() || it->second.slot < 0) {
          if (r->is_prefill) continue;  // job died with its session
          SendOpErrFrame(r->conn, r->id,
                       it == sessions_.end() ? "unknown decode session"
                                             : "decode session evicted");
          r->conn->NotePending(-1);
          continue;
        }
        // plane routing: a speculative session only accepts
        // SPEC_STEP rounds (and its own server-internal prefill) —
        // mixing plain steps in would desync the committed history
        if (r->is_spec) {
          if (!it->second.spec) {
            SendOpErrFrame(r->conn, r->id,
                         "not a speculative session (open it with "
                         "DECODE_SPEC_OPEN)");
            r->conn->NotePending(-1);
            continue;
          }
          if (prefills_.count(r->session)) {
            SendOpErrFrame(r->conn, r->id, "session is still prefilling");
            r->conn->NotePending(-1);
            continue;
          }
          it->second.last_us = uint64_t(ptpu::NowUs());
          it->second.pinned = true;
          spec_rounds.push_back(r);
          continue;
        }
        if (it->second.spec && !r->is_prefill) {
          SendOpErrFrame(r->conn, r->id,
                       "speculative session: use DECODE_SPEC_STEP");
          r->conn->NotePending(-1);
          continue;
        }
        it->second.last_us = uint64_t(ptpu::NowUs());
        it->second.pinned = true;
        sids.push_back(it->second.slot);
        toks.push_back(r->token);
        live.push_back(r);
      }
      // collection done: no further restores can run before the step
      // itself (kv_mu_ stays held), so the pins have done their job
      for (auto* r : live) sessions_[r->session].pinned = false;
      for (auto* r : spec_rounds) sessions_[r->session].pinned = false;
    }
    if (!live.empty()) PlainStepRun(live, sids, toks);
    if (!spec_rounds.empty()) RunSpecRounds(spec_rounds);
  }

  // the width-1 target run (plain steps + prefill chunks); kv_mu_ held
  void PlainStepRun(std::vector<SvRequest*>& live,
                    std::vector<int64_t>& sids,
                    std::vector<int64_t>& toks) {
    char err[512] = {0};
    // smallest ladder bucket holding the sub-run: partial fill stops
    // padding to the baked batch (r9 served every step at B rows)
    PTPU_Predictor* pred = DecBucket(live.size());
    const int64_t t0 = ptpu::NowUs();
    if (ptpu_predictor_decode_step(pred, sids.data(), toks.data(),
                                   int(live.size()), err,
                                   sizeof(err)) != 0) {
      /* One request's bad input (an out-of-vocab token failing the
       * embedding Gather, or "kv pool exhausted" under page pressure)
       * must not error its co-batched neighbours: retry each row
       * alone — on the SMALLEST bucket — so only the offending
       * session answers the error. Pays only on the error path. */
      if (live.size() == 1) {
        const std::string why = std::string("decode_step: ") + err;
        if (std::strstr(err, "kv pool exhausted"))
          dstats.pool_exhausted.Add(1);
        StepRowError(live[0], why);
        return;
      }
      PTPU_Predictor* p1 = dec_buckets.begin()->second;
      for (size_t r2 = 0; r2 < live.size(); ++r2) {
        char rerr[512] = {0};
        const int64_t sid1[1] = {sids[r2]}, tok1[1] = {toks[r2]};
        const int64_t rt0 = ptpu::NowUs();
        if (ptpu_predictor_decode_step(p1, sid1, tok1, 1, rerr,
                                       sizeof(rerr)) != 0) {
          if (std::strstr(rerr, "kv pool exhausted"))
            dstats.pool_exhausted.Add(1);
          StepRowError(live[r2], std::string("decode_step: ") + rerr);
          continue;
        }
        const int64_t rt1 = ptpu::NowUs();
        dstats.batches.Add(1);
        dstats.batch_fill.Observe(1);
        // detach this step's outputs; the reply's logits segment pins
        // them until the net core flushes (ISSUE 17b)
        auto rp1 = std::make_shared<SvReplyPin>();
        rp1->opin = ptpu_predictor_outputs_detach(p1);
        const float* lg1 =
            rp1->opin ? ptpu_outputs_pin_data(rp1->opin, 0) : nullptr;
        if (!lg1) {
          StepRowError(live[r2], "decode: no logits output");
          continue;
        }
        if (live[r2]->is_prefill)
          PrefillRowDone(live[r2], lg1, 0);
        else
          DecodeReply(live[r2], lg1, 0, rt0, rt1, rp1);
      }
      return;
    }
    const int64_t t1 = ptpu::NowUs();
    dstats.run_us.Observe(uint64_t(t1 - t0));
    dstats.batches.Add(1);
    dstats.batch_fill.Observe(uint64_t(live.size()));
    /* Detach the whole step's outputs once: every row's DECODE_REP
     * shares ONE pin, each pointing its logits segment at its own row
     * of the pinned block — no per-row copy, and a slow reader on one
     * conn cannot stall the others (the pin outlives the slowest
     * flush). Prefill rows read their logits transiently before this
     * scope ends, which the local rp reference guarantees. */
    auto rp = std::make_shared<SvReplyPin>();
    rp->opin = ptpu_predictor_outputs_detach(pred);
    const float* lg =
        rp->opin ? ptpu_outputs_pin_data(rp->opin, 0) : nullptr;
    if (!lg) {
      for (auto* r : live) StepRowError(r, "decode: no logits output");
      return;
    }
    for (size_t r2 = 0; r2 < live.size(); ++r2) {
      if (live[r2]->is_prefill)
        PrefillRowDone(live[r2], lg, int64_t(r2));
      else
        DecodeReply(live[r2], lg, int64_t(r2), t0, t1, rp);
    }
  }

  /* ---- speculative rounds (ISSUE 13 tentpole; kv_mu_ held) ----
   * One call drives a full draft/verify round for every row (the
   * re-pack guarantees unique sessions per sub-run):
   *   1. draft catch-up + burst: sequential width-1 draft steps,
   *      BATCHED ACROSS SESSIONS per iteration through the draft
   *      bucket ladder (row A's step j runs in the same draft batch
   *      as row B's step j). A long catch-up (fresh open after a big
   *      prompt) feeds at most prefill_chunk tokens, then re-enqueues
   *      the round so other sessions' steps interleave.
   *   2. verify: ONE width-(k+1) pass per round through the verify
   *      ladder — scores all k proposals + the bonus position.
   *   3. exact acceptance (greedy prefix match / modified rejection),
   *      commit m + 1 tokens, kv_trim the rejected suffix off the
   *      target (COW pages unref, never mutate) and sync the draft.
   * Rows whose context cannot hold a full round fall back to a plain
   * width-1 target step (accepted = 0) — spec degrades gracefully at
   * the context fence instead of erroring. */
  void RunSpecRounds(std::vector<SvRequest*>& rounds) {
    struct Rctx {
      SvRequest* r = nullptr;
      SpecState* st = nullptr;
      int tslot = -1;
      int64_t k = 0;               // proposals this round
      int64_t catchup = 0;         // committed tokens to feed first
      std::vector<int64_t> feeds;  // draft feed list (grows w/ props)
      int64_t fed = 0;
      std::vector<int64_t> props;
      std::vector<float> q;        // k x vocab draft probs (sampling)
      bool fallback = false, park = false, dead = false;
    };
    const int64_t V = dec_logit_elems;
    std::vector<Rctx> rc(rounds.size());
    {
      ptpu::MutexLock l(sess_mu_);
      for (size_t i = 0; i < rounds.size(); ++i) {
        Rctx& c = rc[i];
        c.r = rounds[i];
        auto it = sessions_.find(c.r->session);
        if (it == sessions_.end() || it->second.slot < 0 ||
            !it->second.spec) {
          // validated at de-queue; re-check after regaining the locks
          SendOpErrFrame(c.r->conn, c.r->id, "decode session lost");
          c.r->conn->NotePending(-1);
          c.dead = true;
          continue;
        }
        c.st = it->second.spec.get();
        c.tslot = it->second.slot;
        const int64_t C0 = int64_t(c.st->committed.size());
        const int64_t catchup = C0 - c.st->draft_len;
        c.k = spec_k;
        if (C0 - 1 + ver_width > dec_ctx ||
            C0 - 1 + c.k > draft_ctx || catchup < 1) {
          c.fallback = true;
          continue;
        }
        if (catchup > prefill_chunk + 1) {
          // chunked draft catch-up: feed one chunk, then re-enqueue
          c.park = true;
          c.feeds.assign(
              c.st->committed.begin() + c.st->draft_len,
              c.st->committed.begin() + c.st->draft_len +
                  prefill_chunk);
        } else {
          c.feeds.assign(c.st->committed.begin() + c.st->draft_len,
                         c.st->committed.end());
        }
        c.catchup = int64_t(c.feeds.size());
      }
    }

    // reply an error + roll the draft back to the committed history
    // (uncommitted proposals it fed become unreadable); target and
    // committed are untouched, so the client may simply retry
    const auto round_error = [&](Rctx& c, const std::string& why) {
      const int64_t fence = int64_t(c.st->committed.size()) - 1;
      if (c.st->draft_len > fence) {
        ptpu_kvpool_trim(draft_pool, c.st->draft_slot, fence);
        c.st->draft_len = fence;
      }
      if (why.find("kv pool exhausted") != std::string::npos)
        dstats.pool_exhausted.Add(1);
      SendOpErrFrame(c.r->conn, c.r->id, why);
      c.r->conn->NotePending(-1);
      c.dead = true;
    };

    // one draft proposal pick off a completed draft step's logits row
    const auto draft_pick = [&](Rctx& c, const float* lg) {
      int64_t d;
      if (c.st->sample) {
        if (c.q.empty()) c.q.resize(size_t(c.k) * size_t(V));
        float* qrow = c.q.data() + int64_t(c.props.size()) * V;
        spec_softmax(lg, V, qrow);
        d = spec_sample(qrow, V, 1.0, spec_u01(&c.st->rng));
      } else {
        d = spec_argmax(lg, V);
      }
      c.props.push_back(d);
      if (int64_t(c.props.size()) < c.k) c.feeds.push_back(d);
    };

    // draft-step completion: count the feed, publish the draft's
    // prompt pages once the catch-up covers them (so later spec opens
    // of a shared prompt adopt on the draft plane too), and pick a
    // proposal when this feed is at/past the committed fence
    const auto feed_done = [&](Rctx& c, const float* lg) {
      const int64_t j = c.fed;
      ++c.fed;
      ++c.st->draft_len;
      dstats.spec_draft_steps.Add(1);
      if (!c.st->draft_published &&
          c.st->draft_len >= c.st->prompt_len &&
          c.st->prompt_len <= draft_ctx) {
        ptpu_kvpool_publish(draft_pool, c.st->draft_slot,
                            c.st->committed.data(), c.st->prompt_len);
        c.st->draft_published = true;
      }
      if (!c.park && j >= c.catchup - 1 &&
          int64_t(c.props.size()) < c.k)
        draft_pick(c, lg);
    };

    // ---- 1. draft bursts: iteration j batches every round's j-th
    // pending draft feed across sessions through the draft ladder
    for (;;) {
      std::vector<Rctx*> part;
      for (auto& c : rc)
        if (!c.dead && !c.fallback && c.fed < int64_t(c.feeds.size()))
          part.push_back(&c);
      if (part.empty()) break;
      for (size_t off = 0; off < part.size();
           off += size_t(draft_batch)) {
        const size_t m =
            std::min(part.size() - off, size_t(draft_batch));
        std::vector<int64_t> dsids(m), dtoks(m);
        for (size_t z = 0; z < m; ++z) {
          dsids[z] = part[off + z]->st->draft_slot;
          dtoks[z] = part[off + z]->feeds[size_t(part[off + z]->fed)];
        }
        char err[512] = {0};
        PTPU_Predictor* dpred = LadderBucket(draft_buckets,
                                             draft_ladder, m);
        const bool ok =
            ptpu_predictor_decode_step(dpred, dsids.data(),
                                       dtoks.data(), int(m), err,
                                       sizeof(err)) == 0;
        const float* lg =
            ok ? ptpu_predictor_output_data(dpred, 0) : nullptr;
        for (size_t z = 0; z < m; ++z) {
          Rctx& c = *part[off + z];
          if (!ok || !lg) {
            // retry alone so one bad row cannot poison neighbours
            char rerr[512] = {0};
            PTPU_Predictor* p1 = draft_buckets.begin()->second;
            const int64_t s1[1] = {dsids[z]}, t1[1] = {dtoks[z]};
            if (ptpu_predictor_decode_step(p1, s1, t1, 1, rerr,
                                           sizeof(rerr)) != 0) {
              round_error(c, std::string("spec draft: ") + rerr);
              continue;
            }
            const float* lg1 = ptpu_predictor_output_data(p1, 0);
            if (!lg1) {
              round_error(c, "spec draft: no logits output");
              continue;
            }
            feed_done(c, lg1);
          } else {
            feed_done(c, lg + int64_t(z) * V);
          }
        }
      }
    }

    // ---- parked rounds re-enqueue (chunked catch-up continues on a
    // later flush so other sessions interleave); a full queue parks
    // them on spec_resume_ exactly like stalled prefill admissions
    for (auto& c : rc) {
      if (c.dead || !c.park) continue;
      SvRequest nr = *c.r;
      std::string why;
      if (!dec_batcher->enqueue(std::move(nr), &why)) {
        ptpu::MutexLock l(sess_mu_);
        spec_resume_.push_back(*c.r);
      }
      c.dead = true;  // this visit is done; no reply yet
    }

    // ---- 2. fallback rows: a plain width-1 target step (context
    // fence) — still answers SPEC_REP so the client sees one token
    {
      std::vector<Rctx*> part;
      for (auto& c : rc)
        if (!c.dead && c.fallback) part.push_back(&c);
      for (size_t off = 0; off < part.size();
           off += size_t(dec_batch)) {
        const size_t m = std::min(part.size() - off, size_t(dec_batch));
        std::vector<int64_t> fsids(m), ftoks(m);
        for (size_t z = 0; z < m; ++z) {
          fsids[z] = part[off + z]->tslot;
          ftoks[z] = part[off + z]->st->committed.back();
        }
        char err[512] = {0};
        PTPU_Predictor* pred = DecBucket(m);
        const bool ok =
            ptpu_predictor_decode_step(pred, fsids.data(),
                                       ftoks.data(), int(m), err,
                                       sizeof(err)) == 0;
        const float* lg =
            ok ? ptpu_predictor_output_data(pred, 0) : nullptr;
        for (size_t z = 0; z < m; ++z) {
          Rctx& c = *part[off + z];
          const float* row = nullptr;
          char rerr[512] = {0};
          if (ok && lg) {
            row = lg + int64_t(z) * V;
          } else {
            PTPU_Predictor* p1 = dec_buckets.begin()->second;
            const int64_t s1[1] = {fsids[z]}, t1[1] = {ftoks[z]};
            if (ptpu_predictor_decode_step(p1, s1, t1, 1, rerr,
                                           sizeof(rerr)) != 0) {
              round_error(c, std::string("spec step: ") + rerr);
              continue;
            }
            row = ptpu_predictor_output_data(p1, 0);
            if (!row) {
              round_error(c, "spec step: no logits output");
              continue;
            }
          }
          const int64_t nt = SpecPick(*c.st, row);
          c.st->committed.push_back(nt);
          dstats.spec_rounds.Add(1);
          dstats.spec_fallbacks.Add(1);
          dstats.spec_tokens.Add(1);
          SendSpecRep(c.r->conn, c.r->id, c.r->session, c.r->wire_tid,
                      0, &nt, 1);
          c.r->conn->NotePending(-1);
          c.dead = true;
        }
      }
    }

    // ---- 3. verify + acceptance + rollback
    std::vector<Rctx*> vpart;
    for (auto& c : rc)
      if (!c.dead) vpart.push_back(&c);
    std::vector<float> pbuf(static_cast<size_t>(V));
    std::vector<float> rbuf(static_cast<size_t>(V));
    for (size_t off = 0; off < vpart.size();
         off += size_t(ver_batch)) {
      const size_t m = std::min(vpart.size() - off, size_t(ver_batch));
      std::vector<int64_t> vsids(m), vtoks(m * size_t(ver_width), 0);
      for (size_t z = 0; z < m; ++z) {
        Rctx& c = *vpart[off + z];
        vsids[z] = c.tslot;
        int64_t* row = vtoks.data() + int64_t(z) * ver_width;
        row[0] = c.st->committed.back();
        for (size_t j = 0; j < c.props.size(); ++j)
          row[1 + j] = c.props[j];
      }
      char err[512] = {0};
      PTPU_Predictor* vpred = LadderBucket(ver_buckets, ver_ladder, m);
      const int64_t t0 = ptpu::NowUs();
      bool ok = ptpu_predictor_decode_step(vpred, vsids.data(),
                                           vtoks.data(), int(m), err,
                                           sizeof(err)) == 0;
      const int64_t t1 = ptpu::NowUs();
      if (ok) dstats.run_us.Observe(uint64_t(t1 - t0));
      const float* lg =
          ok ? ptpu_predictor_output_data(vpred, 0) : nullptr;
      if (ok && !lg) ok = false;
      for (size_t z = 0; z < m; ++z) {
        Rctx& c = *vpart[off + z];
        const float* lgv = nullptr;
        char rerr[512] = {0};
        PTPU_Predictor* p1 = ver_buckets.begin()->second;
        if (ok) {
          lgv = lg + int64_t(z) * ver_width * V;
        } else {
          const int64_t s1[1] = {vsids[z]};
          if (ptpu_predictor_decode_step(
                  p1, s1, vtoks.data() + int64_t(z) * ver_width, 1,
                  rerr, sizeof(rerr)) != 0) {
            round_error(c, std::string("spec verify: ") + rerr);
            continue;
          }
          lgv = ptpu_predictor_output_data(p1, 0);
          if (!lgv) {
            round_error(c, "spec verify: no logits output");
            continue;
          }
        }
        // exact acceptance: greedy longest matching prefix, or
        // modified rejection against the stored draft distribution
        SpecState& st = *c.st;
        const int64_t C0 = int64_t(st.committed.size());
        int64_t acc = 0, next_tok = -1;
        if (!st.sample) {
          while (acc < c.k &&
                 c.props[size_t(acc)] == spec_argmax(lgv + acc * V, V))
            ++acc;
          next_tok = spec_argmax(lgv + acc * V, V);
        } else {
          while (acc < c.k) {
            spec_softmax(lgv + acc * V, V, pbuf.data());
            const float* qrow = c.q.data() + acc * V;
            const int64_t d = c.props[size_t(acc)];
            const double u = spec_u01(&st.rng);
            if (u * double(qrow[d]) < double(pbuf[size_t(d)])) {
              ++acc;
              continue;
            }
            // rejected: one draw from the residual max(0, p - q)
            double norm = 0.0;
            for (int64_t i = 0; i < V; ++i) {
              const float ri =
                  std::max(0.f, pbuf[size_t(i)] - qrow[i]);
              rbuf[size_t(i)] = ri;
              norm += double(ri);
            }
            next_tok =
                norm > 0.0
                    ? spec_sample(rbuf.data(), V, norm,
                                  spec_u01(&st.rng))
                    : spec_sample(pbuf.data(), V, 1.0,
                                  spec_u01(&st.rng));
            break;
          }
          if (next_tok < 0) {  // every proposal accepted: bonus draw
            spec_softmax(lgv + c.k * V, V, pbuf.data());
            next_tok = spec_sample(pbuf.data(), V, 1.0,
                                   spec_u01(&st.rng));
          }
        }
        for (int64_t j = 0; j < acc; ++j)
          st.committed.push_back(c.props[size_t(j)]);
        st.committed.push_back(next_tok);
        // rollback: the verify appended ver_width positions; keep
        // only the accepted prefix (+ the round-opening token)
        ptpu_kvpool_trim(kv_pool, c.tslot, C0 + acc);
        const int64_t fence = int64_t(st.committed.size()) - 1;
        if (st.draft_len > fence) {
          ptpu_kvpool_trim(draft_pool, st.draft_slot, fence);
          st.draft_len = fence;
        }
        dstats.spec_rounds.Add(1);
        dstats.spec_proposed.Add(uint64_t(c.k));
        dstats.spec_accepted.Add(uint64_t(acc));
        dstats.spec_tokens.Add(uint64_t(acc + 1));
        std::vector<int64_t> out(size_t(acc + 1));
        for (int64_t j = 0; j < acc; ++j)
          out[size_t(j)] = c.props[size_t(j)];
        out[size_t(acc)] = next_tok;
        SendSpecRep(c.r->conn, c.r->id, c.r->session, c.r->wire_tid,
                    uint32_t(acc), out.data(), uint32_t(out.size()));
        if (c.r->trace_id) {
          auto& tr = ptpu::trace::Global();
          const uint64_t cid = c.r->conn->id();
          tr.Record(c.r->trace_id, ptpu::trace::kRead, c.r->t_read_us,
                    c.r->t_enq_us, cid, c.r->id);
          tr.Record(c.r->trace_id, ptpu::trace::kQueue, c.r->t_enq_us,
                    c.r->t_deq_us, cid, c.r->session);
          tr.Record(c.r->trace_id, ptpu::trace::kBatch, c.r->t_deq_us,
                    t0, cid, c.r->session);
          tr.Record(c.r->trace_id, ptpu::trace::kDecode, t0, t1, cid,
                    c.r->session);
        }
        c.r->conn->NotePending(-1);
      }
    }
  }

  // ------------------------------------------------------ wire loop

  // One complete frame from the epoll core (event-thread context).
  // INFER enqueues into the batcher; a full queue defers the frame
  // (bounded by kSvDeferBudgetUs) instead of blocking the thread.
  ptpu::net::FrameResult OnFrame(const ptpu::net::ConnPtr& conn,
                                 const uint8_t* req, uint32_t n) {
    using ptpu::net::FrameResult;
    // event-thread CPU attributes to the INFER plane until the tag
    // proves the frame is a decode op
    SvCpuScope cpu(&stats.cpu_us);
    const bool retry = conn->deferred_us() > 0;
    // defer retry fast path: the request was parsed (and its payload
    // copied) on the FIRST attempt and stashed on the conn — retries
    // only re-attempt the enqueue, they never re-parse a multi-MB
    // frame on the event thread while the server is saturated
    if (retry && conn->user) {
      auto* stash = static_cast<SvRequest*>(conn->user);
      std::string why;
      const uint64_t rid = stash->id;
      if (batcher->enqueue(std::move(*stash), &why)) {
        conn->NotePending(1);  // in the batcher: not idle (see
                               // ptpu_net.h NotePending)
        delete stash;
        conn->user = nullptr;
        return FrameResult::kOk;
      }
      if (why == "request queue full" &&
          conn->deferred_us() < kSvDeferBudgetUs)
        return FrameResult::kDefer;  // stash stays for the next try
      delete stash;
      conn->user = nullptr;
      SendErrFrame(conn, rid, why);
      return FrameResult::kOk;
    }
    const auto proto_err = [this] {
      stats.proto_errors.Add(1);
      return FrameResult::kClose;
    };
    if (n < 2) return proto_err();
    if (!retry) stats.bytes_in.Add(4 + uint64_t(n));
    // v2 frames carry [u64 trace id] between [ver][tag] and the v1
    // body; every body offset below shifts by ext
    uint64_t wire_tid = 0;
    uint32_t ext = 0;
    if (req[0] == kSvWireVersionTraced) {
      if (n < 2 + ptpu::trace::kTraceExt) return proto_err();
      wire_tid = ptpu::GetU64(req + 2);  // trace id at payload +2
      ext = ptpu::trace::kTraceExt;
    } else if (req[0] != kSvWireVersion) {
      return proto_err();
    }
    const int64_t t_read =
        conn->frame_recv_us() > 0 ? conn->frame_recv_us()
                                  : ptpu::NowUs();
    const uint8_t tag = req[1];
    if (tag == kTagMetaReq) {
      std::vector<uint8_t> f = conn->AcquireBuf();
      f.resize(4 + 2 + (wire_tid ? 8 : 0) + 4 + meta_json.size());
      const size_t ho = RepHdr(f, kTagMetaRep, wire_tid);
      PutU32(f.data() + ho, uint32_t(meta_json.size()));
      std::memcpy(f.data() + ho + 4, meta_json.data(),
                  meta_json.size());
      stats.bytes_out.Add(f.size());
      if (!conn->SendPayload(std::move(f))) return FrameResult::kClose;
      return FrameResult::kOk;
    }
    if (tag == kTagDecodeOpen || tag == kTagDecodeStep ||
        tag == kTagDecodeClose || tag == kTagDecodeOpen2 ||
        tag == kTagDecodeFork || tag == kTagDecodeSpecOpen ||
        tag == kTagDecodeSpecStep) {
      cpu.c = &dstats.cpu_us;  // decode-plane frame: re-attribute
      if (n < 2 + ext + 8) return proto_err();
      const uint64_t rid = ptpu::GetU64(req + 2 + ext);
      if (!dec_pred) {
        SendOpErrFrame(conn, rid, "decode serving not configured (start "
                                "the server with a decode_model)");
        return FrameResult::kOk;
      }
      if (tag == kTagDecodeOpen2) {
        // [u64 req_id][u32 n_tokens][u32 flags=0][n_tokens x i64]
        if (n < 2 + ext + 8 + 4 + 4) return proto_err();
        const uint32_t ntok = GetU32(req + 10 + ext);
        const uint32_t flags = GetU32(req + 14 + ext);
        if (uint64_t(n) != 2 + ext + 8 + 4 + 4 + 8ull * ntok)
          return proto_err();
        if (flags != 0) {
          SendOpErrFrame(conn, rid, "unknown DECODE_OPEN2 flags");
          return FrameResult::kOk;
        }
        if (ntok < 1 || int64_t(ntok) > dec_ctx) {
          SendOpErrFrame(conn, rid,
                       "prompt length outside [1, context=" +
                           std::to_string(dec_ctx) + "]");
          return FrameResult::kOk;
        }
        std::vector<int64_t> toks(ntok);
        for (uint32_t k = 0; k < ntok; ++k)
          toks[k] = ptpu::GetI64(req + 18 + ext + 8 * size_t(k));
        DecodeOpen2(conn, rid, wire_tid, std::move(toks));
        return FrameResult::kOk;
      }
      if (tag == kTagDecodeFork) {
        if (n != 2 + ext + 8 + 8) return proto_err();
        const uint64_t src = ptpu::GetU64(req + 10 + ext);
        uint64_t nsess = 0;
        std::string why;
        if (!DecodeFork(conn, src, &nsess, &why)) {
          SendOpErrFrame(conn, rid, why);
          return FrameResult::kOk;
        }
        std::vector<uint8_t> f = conn->AcquireBuf();
        f.resize(4 + 2 + (wire_tid ? 8 : 0) + 8 + 8);
        const size_t ho = RepHdr(f, kTagDecodeSess, wire_tid);
        ptpu::PutU64(f.data() + ho, rid);
        ptpu::PutU64(f.data() + ho + 8, nsess);
        stats.bytes_out.Add(f.size());
        if (!conn->SendPayload(std::move(f)))
          return FrameResult::kClose;
        return FrameResult::kOk;
      }
      if (tag == kTagDecodeSpecOpen) {
        // [u64 req_id][u32 n_tokens][u32 flags][u64 seed][n x i64]
        if (n < 2 + ext + 8 + 4 + 4 + 8) return proto_err();
        const uint32_t ntok = GetU32(req + 10 + ext);
        const uint32_t flags = GetU32(req + 14 + ext);
        const uint64_t seed = ptpu::GetU64(req + 18 + ext);
        if (uint64_t(n) != 2 + ext + 8 + 4 + 4 + 8 + 8ull * ntok)
          return proto_err();
        if (spec_k <= 0) {
          SendOpErrFrame(conn, rid,
                       "speculative decoding not configured (start "
                       "the server with spec draft/verify models)");
          return FrameResult::kOk;
        }
        if (flags & ~1u) {
          SendOpErrFrame(conn, rid, "unknown DECODE_SPEC_OPEN flags");
          return FrameResult::kOk;
        }
        if (ntok < 1 || int64_t(ntok) >= dec_ctx) {
          SendOpErrFrame(conn, rid,
                       "prompt length outside [1, context=" +
                           std::to_string(dec_ctx) + ")");
          return FrameResult::kOk;
        }
        std::vector<int64_t> toks(ntok);
        for (uint32_t k = 0; k < ntok; ++k)
          toks[k] = ptpu::GetI64(req + 26 + ext + 8 * size_t(k));
        DecodeSpecOpen(conn, rid, wire_tid, flags, seed,
                       std::move(toks));
        return FrameResult::kOk;
      }
      if (tag == kTagDecodeSpecStep) {
        if (n != 2 + ext + 8 + 8) return proto_err();
        if (spec_k <= 0) {
          SendOpErrFrame(conn, rid,
                       "speculative decoding not configured (start "
                       "the server with spec draft/verify models)");
          return FrameResult::kOk;
        }
        SvRequest r;
        r.is_decode = true;
        r.is_spec = true;
        r.id = rid;
        r.session = ptpu::GetU64(req + 10 + ext);
        r.rows = 1;
        r.conn = conn;
        r.wire_tid = wire_tid;
        // a defer retry re-parses this 18/26-byte frame; only the
        // FIRST attempt rolls the sampling dice
        r.trace_id = retry && !wire_tid
                         ? 0
                         : ptpu::trace::Global().BeginRequest(wire_tid);
        r.t_read_us = t_read;
        r.t_enq_us = ptpu::NowUs();
        if (!retry) dstats.steps.Add(1);
        std::string why;
        if (dec_batcher->enqueue(std::move(r), &why)) {
          conn->NotePending(1);  // pairs with the SPEC_REP/error -1
          return FrameResult::kOk;
        }
        if (why == "request queue full" &&
            conn->deferred_us() < kSvDeferBudgetUs)
          return FrameResult::kDefer;
        SendOpErrFrame(conn, rid, why);
        return FrameResult::kOk;
      }
      if (tag == kTagDecodeOpen) {
        if (n != 2 + ext + 8) return proto_err();
        uint64_t sess = 0;
        std::string why;
        if (!DecodeOpen(conn, &sess, &why)) {
          SendOpErrFrame(conn, rid, why);
          return FrameResult::kOk;
        }
        std::vector<uint8_t> f = conn->AcquireBuf();
        f.resize(4 + 2 + (wire_tid ? 8 : 0) + 8 + 8);
        const size_t ho = RepHdr(f, kTagDecodeSess, wire_tid);
        ptpu::PutU64(f.data() + ho, rid);
        ptpu::PutU64(f.data() + ho + 8, sess);
        stats.bytes_out.Add(f.size());
        if (!conn->SendPayload(std::move(f)))
          return FrameResult::kClose;
        return FrameResult::kOk;
      }
      if (tag == kTagDecodeClose) {
        if (n != 2 + ext + 8 + 8) return proto_err();
        const uint64_t sess = ptpu::GetU64(req + 10 + ext);
        std::string why;
        if (!DecodeClose(sess, &why)) {
          SendOpErrFrame(conn, rid, why);
          return FrameResult::kOk;
        }
        std::vector<uint8_t> f = conn->AcquireBuf();
        f.resize(4 + 2 + (wire_tid ? 8 : 0) + 8 + 8);
        const size_t ho = RepHdr(f, kTagDecodeSess, wire_tid);
        ptpu::PutU64(f.data() + ho, rid);
        ptpu::PutU64(f.data() + ho + 8, sess);
        stats.bytes_out.Add(f.size());
        if (!conn->SendPayload(std::move(f)))
          return FrameResult::kClose;
        return FrameResult::kOk;
      }
      // DECODE_STEP: [ver][tag][u64 req_id][u64 session][i64 token]
      if (n != 2 + ext + 8 + 8 + 8) return proto_err();
      SvRequest r;
      r.is_decode = true;
      r.id = rid;
      r.session = ptpu::GetU64(req + 10 + ext);
      r.token = ptpu::GetI64(req + 18 + ext);
      r.rows = 1;
      r.conn = conn;
      r.wire_tid = wire_tid;
      // a defer retry re-parses this 26/34-byte frame; only the FIRST
      // attempt rolls the sampling dice (retries reuse the client id)
      r.trace_id = retry && !wire_tid
                       ? 0
                       : ptpu::trace::Global().BeginRequest(wire_tid);
      r.t_read_us = t_read;
      r.t_enq_us = ptpu::NowUs();
      if (!retry) dstats.steps.Add(1);
      std::string why;
      if (dec_batcher->enqueue(std::move(r), &why)) {
        conn->NotePending(1);  // pairs with the reply/error -1
        return FrameResult::kOk;
      }
      if (why == "request queue full" &&
          conn->deferred_us() < kSvDeferBudgetUs)
        return FrameResult::kDefer;  // cheap 26-byte re-parse on retry
      SendOpErrFrame(conn, rid, why);
      return FrameResult::kOk;
    }
    if (tag != kTagInferReq) return proto_err();
    // [u64 req_id][u16 n_inputs] per input:
    // [u8 dtype][u8 ndim][ndim x i64][raw]
    if (n < 2 + ext + 8 + 2) return proto_err();
    SvRequest r;
    /* In-place ingestion (ISSUE 17a): pin the conn's reassembly
     * buffer once for the whole request — every input payload below
     * becomes a borrowed view into the wire bytes instead of a copy.
     * The pin survives kDefer stashes (the event loop swaps in a
     * fresh buffer rather than compacting a pinned one, so stashed
     * views never move) and rides into the batcher, released with the
     * request after the gather. nullptr = a Detached conn pumping
     * caller-owned memory (fuzz harnesses): inputs copy as before. */
    r.pin = conn->PinInbuf(req, n);
    std::memcpy(&r.id, req + 2 + ext, 8);
    uint16_t nin;
    std::memcpy(&nin, req + 10 + ext, 2);
    size_t off = 12 + ext;
    std::string bad;
    if (nin != sig.size())
      bad = "expected " + std::to_string(sig.size()) +
            " inputs, got " + std::to_string(nin);
    r.inputs.resize(sig.size());
    int64_t rows = -1;
    for (size_t i = 0; bad.empty() && i < sig.size(); ++i) {
      if (n < off + 2) return proto_err();
      const int dt = req[off];
      const int nd = req[off + 1];
      off += 2;
      if (nd < 1 || nd > kSvMaxNdim || n < off + size_t(nd) * 8)
        return proto_err();
      SvInput& in = r.inputs[i];
      in.dtype = dt;
      in.dims.resize(size_t(nd));
      std::memcpy(in.dims.data(), req + off, size_t(nd) * 8);
      off += size_t(nd) * 8;
      if (dt != sig[i].dtype) {
        bad = "input '" + sig[i].name + "': dtype " +
              std::to_string(dt) + " != model dtype " +
              std::to_string(sig[i].dtype);
        break;
      }
      if (size_t(nd) != sig[i].tail.size() + 1) {
        bad = "input '" + sig[i].name + "': ndim " +
              std::to_string(nd) + " != " +
              std::to_string(sig[i].tail.size() + 1);
        break;
      }
      for (size_t k = 0; k < sig[i].tail.size(); ++k)
        if (in.dims[k + 1] != sig[i].tail[k]) {
          bad = "input '" + sig[i].name +
                "': non-batch dims do not match the model";
          break;
        }
      if (!bad.empty()) break;
      if (in.dims[0] < 1) {
        bad = "input '" + sig[i].name + "': batch dim must be >= 1";
        break;
      }
      if (rows < 0) rows = in.dims[0];
      else if (in.dims[0] != rows) {
        bad = "inputs disagree on the batch dim";
        break;
      }
      const size_t nb = size_t(in.dims[0]) *
                        size_t(sig[i].row_elems) *
                        size_t(sv_dtype_size(sig[i].dtype));
      if (n < off + nb) return proto_err();
      if (r.pin) {
        in.ext = req + off;
        in.ext_n = nb;
      } else {
        // unpinnable (Detached) conn: dynamic fallback to the
        // copying path — the view would dangle past the handler
        in.data.assign(req + off, req + off + nb);
      }
      off += nb;
    }
    if (!retry) stats.requests.Add(1);
    if (!bad.empty()) {
      SendErrFrame(conn, r.id, bad);
      return FrameResult::kOk;
    }
    r.rows = rows;
    r.conn = conn;
    r.wire_tid = wire_tid;
    r.trace_id = ptpu::trace::Global().BeginRequest(wire_tid);
    r.t_read_us = t_read;
    r.t_enq_us = ptpu::NowUs();
    std::string why;
    const uint64_t rid = r.id;
    if (batcher->enqueue(std::move(r), &why)) {
      conn->NotePending(1);  // in the batcher: not idle until replied
      return FrameResult::kOk;
    }
    {
      // enqueue moves the request only on success, so r is intact
      if (why == "request queue full" &&
          conn->deferred_us() < kSvDeferBudgetUs) {
        // stash the parsed request; the event loop re-dispatches this
        // frame and the retry fast path above re-attempts the enqueue
        // (t_enq_us keeps the FIRST attempt's stamp, so e2e_us spans
        // the whole deferred wait like the old blocking retries)
        conn->user = new SvRequest(std::move(r));
        return FrameResult::kDefer;
      }
      SendErrFrame(conn, rid, why);
    }
    return FrameResult::kOk;
  }

  void Stop() {
    if (stop.exchange(true)) return;
    // graceful drain: stop accepting -> let the batcher workers
    // finish EVERYTHING queued (in-flight requests still answer over
    // still-open conns) -> flush queued replies -> close. The batcher
    // objects stay alive until the event threads are joined — they
    // may still call enqueue(), which answers "server stopping" on a
    // stopped batcher but would be UB on a destroyed one.
    if (net_srv) net_srv->StopAccepting();
    std::deque<SvRequest> leftover;
    if (batcher) leftover = batcher->stop();
    if (dec_batcher) {
      auto dec_left = dec_batcher->stop();
      for (auto& r : dec_left) leftover.push_back(std::move(r));
      // spec rounds parked mid-catch-up by a full queue still owe a
      // reply (their NotePending +1 is live)
      ptpu::MutexLock l(sess_mu_);
      for (auto& r : spec_resume_) leftover.push_back(std::move(r));
      spec_resume_.clear();
    }
    for (auto& r : leftover) {
      if (r.is_prefill) {
        // the job answers its OPEN2 once, not per queued step
        ptpu::MutexLock l(sess_mu_);
        auto it = prefills_.find(r.session);
        if (it != prefills_.end()) {
          SendOpErrFrame(it->second->conn, it->second->rid,
                       "server stopping");
          it->second->conn->NotePending(-1);
          prefills_.erase(it);
        }
        continue;
      }
      // leftover deque mixes INFER requests (counted in
      // stats.requests) with decode steps/rounds (counted in
      // dstats.steps): answer each on its own error ledger
      if (r.is_decode)
        SendOpErrFrame(r.conn, r.id, "server stopping");
      else
        SendErrFrame(r.conn, r.id, "server stopping");
      r.conn->NotePending(-1);  // pairs the enqueue-time +1
    }
    if (net_srv) {
      net_srv->Drain();
      net_srv.reset();
    }
    // conservation-law gate (ISSUE 20): the server is quiescent here
    // — drained, every queued request answered, sessions and pools
    // still alive — exactly when the `==` laws must hold. Logs the
    // report on violation (PTPU_INVAR_OFF=1 disables); selftests and
    // benches assert the same report is clean via the ABI.
    ptpu::invar::GateQuiesced(StatsJson(), "serving", "serving.Stop");
    batcher.reset();
    dec_batcher.reset();
    // prefix-cache persistence (ISSUE 19): snapshot the adopt index
    // before the pool dies; the next start warms from it (load
    // re-keys by token ids, so a stale file can only miss)
    if (kv_pool && !prefix_persist_path.empty()) {
      char perr[256] = {0};
      ptpu_kvpool_prefix_save(kv_pool, prefix_persist_path.c_str(),
                              perr, sizeof(perr));
    }
    for (auto& kv2 : dec_buckets)
      if (kv2.second != dec_pred) ptpu_predictor_destroy(kv2.second);
    dec_buckets.clear();
    dec_ladder.clear();
    // spec planes: predictors before their pools (a pool must outlive
    // every predictor attached to it)
    for (auto& kv2 : ver_buckets) ptpu_predictor_destroy(kv2.second);
    ver_buckets.clear();
    ver_ladder.clear();
    for (auto& kv2 : draft_buckets) ptpu_predictor_destroy(kv2.second);
    draft_buckets.clear();
    draft_ladder.clear();
    if (dec_pred) {
      ptpu_predictor_destroy(dec_pred);
      dec_pred = nullptr;
    }
    if (kv_pool) {
      ptpu_kvpool_destroy(kv_pool);
      kv_pool = nullptr;
    }
    if (draft_pool) {
      ptpu_kvpool_destroy(draft_pool);
      draft_pool = nullptr;
    }
    if (dec_pool) {
      ptpu_workpool_destroy(dec_pool);
      dec_pool = nullptr;
    }
    // shadow plane: predictors before their pool
    for (auto& kv2 : shadow_buckets) ptpu_predictor_destroy(kv2.second);
    shadow_buckets.clear();
    if (shadow_pool) {
      ptpu_workpool_destroy(shadow_pool);
      shadow_pool = nullptr;
    }
  }

  // --------------------------------------------------------- stats
  std::string StatsJson() {
    std::string out = "{\"server\":{";
    const struct {
      const char* name;
      const ptpu::Counter* c;
    } cs[] = {
        {"requests", &stats.requests},
        {"replies", &stats.replies},
        {"req_errors", &stats.req_errors},
        {"op_errors", &stats.op_errors},
        {"err_frames", &stats.err_frames},
        {"proto_errors", &stats.proto_errors},
        {"handshake_fails", &net.handshake_fails},
        {"conns_accepted", &net.conns_accepted},
        {"conns_closed", &net.conns_closed},
        {"conns_shed", &net.conns_shed},
        {"handshake_timeouts", &net.handshake_timeouts},
        {"idle_closes", &net.idle_closes},
        {"epoll_wakeups", &net.epoll_wakeups},
        {"partial_write_flushes", &net.partial_write_flushes},
        {"http_reqs", &net.http_reqs},
        {"chaos_conn_kills", &net.chaos_conn_kills},
        {"chaos_read_delays", &net.chaos_read_delays},
        {"chaos_write_delays", &net.chaos_write_delays},
        {"chaos_short_writes", &net.chaos_short_writes},
        {"chaos_handshake_drops", &net.chaos_handshake_drops},
        {"bytes_in", &stats.bytes_in},
        {"bytes_out", &stats.bytes_out},
        {"cpu_us", &stats.cpu_us},
    };
    for (const auto& kv : cs) {
      ptpu::AppendJsonU64(&out, kv.name, kv.c->Get());
      out += ',';
    }
    ptpu::AppendJsonU64(
        &out, "conns_active",
        uint64_t(net.active_conns.load(std::memory_order_relaxed)));
    out += "},\"batcher\":{";
    const struct {
      const char* name;
      const ptpu::Counter* c;
    } bs[] = {
        {"batches", &stats.batches},
        {"batched_requests", &stats.batched_requests},
        {"batched_rows", &stats.batched_rows},
        {"bucket_miss", &stats.bucket_miss},
        {"full_flushes", &stats.full_flushes},
        {"deadline_flushes", &stats.deadline_flushes},
    };
    for (const auto& kv : bs) {
      ptpu::AppendJsonU64(&out, kv.name, kv.c->Get());
      out += ',';
    }
    // bucket-ladder coverage: runs that fell off a planned arena,
    // summed over every instance's bucket predictors (delta since the
    // last stats_reset — see dyn_fallback_base_)
    const uint64_t dyn = DynFallbackSum();
    const uint64_t base =
        dyn_fallback_base_.load(std::memory_order_relaxed);
    ptpu::AppendJsonU64(&out, "dynamic_shape_fallback",
                        dyn > base ? dyn - base : 0);
    out += ',';
    ptpu::AppendJsonHist(&out, "queue_depth", stats.queue_depth);
    out += ',';
    ptpu::AppendJsonHist(&out, "batch_fill", stats.batch_fill);
    out += ',';
    ptpu::AppendJsonHist(&out, "e2e_us", stats.e2e_us);
    out += ',';
    ptpu::AppendJsonHist(&out, "run_us", stats.run_us);
    out += "}";
    if (dec_pred) {
      out += ",\"decode\":{";
      const struct {
        const char* name;
        const ptpu::Counter* c;
      } ds[] = {
          {"opens", &dstats.opens},
          {"closes", &dstats.closes},
          {"evictions", &dstats.evictions},
          {"steps", &dstats.steps},
          {"replies", &dstats.replies},
          {"batches", &dstats.batches},
          {"prefills", &dstats.prefills},
          {"prefill_tokens", &dstats.prefill_tokens},
          {"prefill_adopted", &dstats.prefill_adopted},
          {"forks", &dstats.forks},
          {"pool_exhausted", &dstats.pool_exhausted},
          {"bucket_miss", &dstats.bucket_miss},
          {"spec_rounds", &dstats.spec_rounds},
          {"spec_proposed", &dstats.spec_proposed},
          {"spec_accepted", &dstats.spec_accepted},
          {"spec_tokens", &dstats.spec_tokens},
          {"spec_draft_steps", &dstats.spec_draft_steps},
          {"spec_fallbacks", &dstats.spec_fallbacks},
          {"hibernates", &dstats.hibernates},
          {"restores", &dstats.restores},
          {"spill_exhausted", &dstats.spill_exhausted},
          {"cpu_us", &dstats.cpu_us},
      };
      for (const auto& kv : ds) {
        ptpu::AppendJsonU64(&out, kv.name, kv.c->Get());
        out += ',';
      }
      uint64_t live = 0, hibernated = 0;
      {
        ptpu::MutexLock l(sess_mu_);
        for (const auto& kv : sessions_) {
          if (kv.second.slot >= 0) ++live;
          if (kv.second.slot < 0 && !kv.second.hib.empty())
            ++hibernated;
        }
      }
      ptpu::AppendJsonU64(&out, "sessions_active", live);
      out += ',';
      // ISSUE 19 gauges: sessions holding pool pages vs. sessions
      // whose pool state lives in the spill tier (slot freed)
      ptpu::AppendJsonU64(&out, "sessions_resident", live);
      out += ',';
      ptpu::AppendJsonU64(&out, "sessions_hibernated", hibernated);
      out += ',';
      ptpu::AppendJsonU64(&out, "kv_sessions", uint64_t(kv_sessions));
      out += ',';
      ptpu::AppendJsonU64(&out, "spec_k", uint64_t(spec_k));
      out += ',';
      ptpu::AppendJsonHist(&out, "run_us", dstats.run_us);
      out += ',';
      ptpu::AppendJsonHist(&out, "batch_fill", dstats.batch_fill);
      out += ',';
      ptpu::AppendJsonHist(&out, "restore_us", dstats.restore_us);
      if (kv_pool) {
        // pages_in_use/pages_total gauges + prefix_hits/cow_copies
        // live in the pool's own snapshot (rendered in the predictor
        // .so — one source of truth for the pager's counters).
        // ptpu_kvpool_stats_json caches its snapshot in the pool
        // handle ("valid until the next call"), and StatsJson runs
        // concurrently on every telemetry event thread: serialize
        // the call AND the copy-out under sess_mu_.
        ptpu::MutexLock l(sess_mu_);
        out += ",\"pool\":";
        out += ptpu_kvpool_stats_json(kv_pool);
      }
      out += '}';
    }
    out += ",\"shadow\":";
    out += ShadowJson();
    out += "}";
    return out;
  }

  // The `shadow` stats object / GET /shadowz body. u64-only (diffs
  // and tolerance in 1e-9 units) so /metrics renders it as counters.
  std::string ShadowJson() {
    std::string out = "{";
    ptpu::AppendJsonU64(&out, "enabled",
                        shadow_buckets.empty() ? 0 : 1);
    out += ',';
    ptpu::AppendJsonU64(&out, "sample", uint64_t(shadow_sample));
    out += ',';
    ptpu::AppendJsonU64(&out, "tol_e9",
                        uint64_t(std::min(shadow_tol * 1e9, 1e18)));
    out += ',';
    const struct {
      const char* name;
      const ptpu::Counter* c;
    } ss[] = {
        {"batches", &sstats.batches},
        {"requests", &sstats.requests},
        {"mismatched_batches", &sstats.mismatched_batches},
        {"run_errors", &sstats.run_errors},
        {"primary_run_us", &sstats.primary_run_us},
        {"shadow_run_us", &sstats.shadow_run_us},
    };
    for (const auto& kv : ss) {
      ptpu::AppendJsonU64(&out, kv.name, kv.c->Get());
      out += ',';
    }
    ptpu::AppendJsonU64(
        &out, "max_abs_diff_e9",
        sstats.max_abs_diff_e9.load(std::memory_order_relaxed));
    out += '}';
    return out;
  }

  uint64_t DynFallbackSum() const {
    uint64_t dyn = 0;
    for (const auto& inst : insts)
      for (const auto& kv : inst->buckets)
        dyn += uint64_t(ptpu_predictor_dynamic_fallbacks(kv.second));
    return dyn;
  }

  /* Reset zeroes the serving counters only. The bucket predictors'
   * own stats are NOT reset — an instance worker may be mid-run, and
   * ptpu_predictor_stats_reset rebuilds structures run() is holding
   * pointers into (the predictor is thread-compatible, not
   * thread-safe). dynamic_shape_fallback instead resets by baseline
   * subtraction against the predictors' monotonic atomic counters. */
  std::atomic<uint64_t> dyn_fallback_base_{0};

  void StatsReset() {
    stats.Reset();
    net.Reset();
    dstats.Reset();
    dec_bstats.Reset();
    sstats.Reset();
    dyn_fallback_base_.store(DynFallbackSum(),
                             std::memory_order_relaxed);
  }
};

thread_local std::string g_sv_json;

}  // namespace

extern "C" {

/* Extended start (ISSUE 13): speculative decoding. spec_draft_path is
 * a SMALL model's width-1 decode artifact; spec_verify_path is the
 * TARGET model exported at width k+1
 * (models.gpt.export_gpt_decode(width=k+1)). Both NULL/empty disables
 * speculation; passing only one fails. k derives from the verify
 * artifact's width (capped by $PTPU_SPEC_K). Enables the
 * DECODE_SPEC_OPEN/STEP wire ops (0x6d/0x6e -> 0x6f replies carrying
 * per-round accept counts). Everything else is ptpu_serving_start3. */
__attribute__((visibility("default")))
void* ptpu_serving_start4(const char* model_path,
                          const char* decode_model_path,
                          const char* spec_draft_path,
                          const char* spec_verify_path, int port,
                          const char* authkey, int authkey_len,
                          int max_batch, int64_t deadline_us,
                          int instances, int threads_per_instance,
                          int loopback_only, int kv_sessions,
                          int http_port, char* err, int err_len) {
  auto* s = new SvServer();
  try {
    s->model_path = model_path ? model_path : "";
    s->decode_model_path =
        decode_model_path ? decode_model_path : "";
    s->spec_draft_path = spec_draft_path ? spec_draft_path : "";
    s->spec_verify_path = spec_verify_path ? spec_verify_path : "";
    s->kv_sessions = kv_sessions;
    s->authkey.assign(authkey ? authkey : "",
                      authkey_len > 0 ? size_t(authkey_len) : 0);
    s->max_batch = max_batch > 0 ? max_batch : 8;
    s->deadline_us = deadline_us > 0 ? deadline_us : 2000;
    s->instances = instances > 0 ? instances : 2;
    s->threads_per_instance = threads_per_instance;
    s->http_port_want = http_port;
    s->Start(port, loopback_only);
    return s;
  } catch (const std::exception& e) {
    if (err && err_len > 0)
      std::snprintf(err, size_t(err_len), "%s", e.what());
    delete s;
    return nullptr;
  }
}

/* Extended start (ISSUE 10): http_port >= 0 adds the telemetry
 * HTTP/1.1 listener (GET /metrics /healthz /statsz /tracez; 0 picks a
 * free port — ptpu_serving_http_port reports it) on the same epoll
 * event threads. Everything else is ptpu_serving_start2. */
__attribute__((visibility("default")))
void* ptpu_serving_start3(const char* model_path,
                          const char* decode_model_path, int port,
                          const char* authkey, int authkey_len,
                          int max_batch, int64_t deadline_us,
                          int instances, int threads_per_instance,
                          int loopback_only, int kv_sessions,
                          int http_port, char* err, int err_len) {
  return ptpu_serving_start4(model_path, decode_model_path, nullptr,
                             nullptr, port, authkey, authkey_len,
                             max_batch, deadline_us, instances,
                             threads_per_instance, loopback_only,
                             kv_sessions, http_port, err, err_len);
}

/* Extended start (r9): `decode_model_path` (may be NULL/empty) adds
 * the KV-cached DECODE plane — a decode-step artifact served through
 * its own predictor + micro-batcher with `kv_sessions` per-session KV
 * slots (<= 0: $PTPU_KV_SESSIONS, default 64). Everything else is
 * ptpu_serving_start. */
__attribute__((visibility("default")))
void* ptpu_serving_start2(const char* model_path,
                          const char* decode_model_path, int port,
                          const char* authkey, int authkey_len,
                          int max_batch, int64_t deadline_us,
                          int instances, int threads_per_instance,
                          int loopback_only, int kv_sessions, char* err,
                          int err_len) {
  return ptpu_serving_start3(model_path, decode_model_path, port,
                             authkey, authkey_len, max_batch,
                             deadline_us, instances,
                             threads_per_instance, loopback_only,
                             kv_sessions, -1, err, err_len);
}

__attribute__((visibility("default")))
void* ptpu_serving_start(const char* model_path, int port,
                         const char* authkey, int authkey_len,
                         int max_batch, int64_t deadline_us,
                         int instances, int threads_per_instance,
                         int loopback_only, char* err, int err_len) {
  return ptpu_serving_start2(model_path, nullptr, port, authkey,
                             authkey_len, max_batch, deadline_us,
                             instances, threads_per_instance,
                             loopback_only, 0, err, err_len);
}

// Handle-taking entries guard NULL (a failed start returns NULL; a
// binding must be able to pass that back without a segfault).
__attribute__((visibility("default")))
int ptpu_serving_port(void* h) {
  auto* s = static_cast<SvServer*>(h);
  return s ? s->port : -1;
}

// Telemetry HTTP port, or -1 when the endpoint is disabled.
__attribute__((visibility("default")))
int ptpu_serving_http_port(void* h) {
  auto* s = static_cast<SvServer*>(h);
  if (!s || !s->net_srv) return -1;
  return s->net_srv->http_port();
}

/* Two-phase shutdown, half one: stop accepting framed connections
 * and flip GET /healthz to 503 {"status":"draining"} while existing
 * connections (and the HTTP listener) keep answering — take the node
 * out of the load balancer, let in-flight work finish, THEN call
 * ptpu_serving_stop. Idempotent. */
__attribute__((visibility("default")))
void ptpu_serving_drain_begin(void* h) {
  auto* s = static_cast<SvServer*>(h);
  if (!s) return;
  s->DrainBegin();
}

// Prometheus exposition text of the live stats snapshot — the same
// bytes GET /metrics serves (byte-identical to profiler/stats.py
// prometheus_text over the stats_json snapshot). Thread-local buffer,
// valid until this thread's next call.
__attribute__((visibility("default")))
const char* ptpu_serving_prom_text(void* h) {
  auto* s = static_cast<SvServer*>(h);
  if (!s) return "";
  thread_local std::string g_prom;
  g_prom = ptpu::trace::PromFromStatsJson(s->StatsJson(),
                                          "ptpu_serving");
  return g_prom.c_str();
}

__attribute__((visibility("default")))
const char* ptpu_serving_config_json(void* h) {
  auto* s = static_cast<SvServer*>(h);
  if (!s) return "{}";
  g_sv_json = s->meta_json;
  return g_sv_json.c_str();
}

__attribute__((visibility("default")))
const char* ptpu_serving_stats_json(void* h) {
  auto* s = static_cast<SvServer*>(h);
  if (!s) return "{}";
  g_sv_json = s->StatsJson();
  return g_sv_json.c_str();
}

__attribute__((visibility("default")))
void ptpu_serving_stats_reset(void* h) {
  auto* s = static_cast<SvServer*>(h);
  if (!s) return;
  s->StatsReset();
}

__attribute__((visibility("default")))
void ptpu_serving_stop(void* h) {
  auto* s = static_cast<SvServer*>(h);
  if (!s) return;
  s->Stop();
  delete s;
}

}  // extern "C"
