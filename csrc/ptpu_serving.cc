// C-hosted concurrent inference serving runtime — the wire + batching
// half of native serving (csrc/ptpu_predictor.cc holds the execution
// half, reached ONLY through its public C ABI in
// csrc/ptpu_inference_api.h so the layering stays testable).
//
// Reference counterpart: the multi-threaded serving stack over
// AnalysisPredictor — `paddle_infer::services::PredictorPool` fanned
// out behind a request server, plus the dynamic batching every
// serving system grows (Clipper NSDI'17; batching queues in Orca
// OSDI'22). Three pieces:
//
//   * Parallel instances: N serving instances, each owning a PRIVATE
//     WorkPool sub-pool (ptpu_workpool_create) attached to all of its
//     predictors, so concurrent batches execute truly in parallel
//     instead of serializing on the global dispatch mutex.
//   * Dynamic micro-batcher: a lock+condvar FIFO of requests that
//     flushes when `max_batch` rows accumulate or `deadline_us` has
//     passed since the oldest queued request; requests are stitched
//     into one batched run and de-muxed row-wise, strictly FIFO.
//   * Bucket ladder: at load time the artifact is re-planned for
//     batch sizes {1,2,4,...,max_batch} (ptpu_predictor_create_opts
//     batch_override), so every batched run binds into a pre-planned
//     arena — zero per-run allocation. A flush whose row count has no
//     exact bucket pads up to the next one (counted in bucket_miss);
//     runs that still fall off a planned arena surface in
//     dynamic_shape_fallback.
//
// Wire protocol (mirrors the PS data plane, csrc/ptpu_ps_server.cc):
//   * connect: 16-byte nonce -> HMAC-SHA256(authkey, nonce) frame ->
//     one byte 0x01 (csrc/ptpu_hmac.h).
//   * frames: u32-LE length prefix + payload both ways; payload leads
//     with [u8 version][u8 tag].
//       0x60 INFER_REQ  [u64 req_id][u16 n_inputs] then per input
//                       [u8 onnx_dtype][u8 ndim][ndim x i64 dims][raw]
//       0x61 INFER_REP  [u64 req_id][u16 n_outputs] then per output
//                       [u8 ndim][ndim x i64 dims][f32 raw]
//       0x62 INFER_ERR  [u64 req_id][u32 len][msg]
//       0x63 META_REQ   (empty) -> 0x64 META_REP [u32 len][json]
//   req_id is caller-chosen; replies may interleave across a
//   connection's in-flight requests (client pipelining).
//
// Build: linked with ptpu_predictor.cc into
// paddle_tpu/_native_predictor.so (csrc/Makefile); unit-tested by
// csrc/ptpu_serving_selftest.cc.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

#include "ptpu_hmac.h"
#include "ptpu_inference_api.h"
#include "ptpu_stats.h"
#include "ptpu_sync.h"
#include "ptpu_wire.h"

namespace {

constexpr uint8_t kSvWireVersion = 1;
constexpr uint8_t kTagInferReq = 0x60;
constexpr uint8_t kTagInferRep = 0x61;
constexpr uint8_t kTagInferErr = 0x62;
constexpr uint8_t kTagMetaReq = 0x63;
constexpr uint8_t kTagMetaRep = 0x64;
constexpr uint32_t kSvMaxFrame = 1u << 30;
constexpr int kSvMaxNdim = 16;

// ONNX TensorProto dtype codes accepted on the wire
enum { SV_F32 = 1, SV_I32 = 6, SV_I64 = 7 };

inline int sv_dtype_size(int dt) {
  return dt == SV_I64 ? 8 : dt == SV_I32 || dt == SV_F32 ? 4 : 0;
}

// exact I/O + frame codec live in the shared csrc/ptpu_wire.h
using ptpu::GetU32;
using ptpu::PutU32;
using ptpu::ReadExact;
using ptpu::WriteExact;

/* One client connection. Replies are written by batcher instance
 * threads while the conn's reader thread parses the next request, so
 * writes serialize on wmu; `closed` keeps a late reply from writing
 * into a recycled fd. */
struct SvConn {
  int fd = -1;
  std::mutex wmu;
  bool closed = false;

  bool Send(const std::vector<uint8_t>& frame) {
    std::lock_guard<std::mutex> g(wmu);
    if (closed) return false;
    if (!WriteExact(fd, frame.data(), frame.size())) {
      // SO_SNDTIMEO expired (client stopped reading) or hard error:
      // break the connection so instance workers never stall on it
      // again and the reader thread unblocks
      closed = true;
      ::shutdown(fd, SHUT_RDWR);
      return false;
    }
    return true;
  }

  void Close() {
    std::lock_guard<std::mutex> g(wmu);
    if (!closed) {
      closed = true;
      ::shutdown(fd, SHUT_RDWR);
    }
  }
};

struct SvInput {
  int dtype = SV_F32;
  std::vector<int64_t> dims;
  std::vector<uint8_t> data;
};

struct SvRequest {
  uint64_t id = 0;
  int64_t rows = 0;
  std::vector<SvInput> inputs;
  std::shared_ptr<SvConn> conn;
  int64_t t_enq_us = 0;
};

// Always-on counters/histograms (csrc/ptpu_stats.h relaxed atomics).
struct SvStats {
  ptpu::Counter requests, replies, req_errors, batches,
      batched_requests, batched_rows, bucket_miss, full_flushes,
      deadline_flushes, bytes_in, bytes_out, err_frames, proto_errors,
      handshake_fails, conns_accepted;
  std::atomic<int64_t> conns_active{0};
  ptpu::Histogram queue_depth, batch_fill, e2e_us, run_us;

  void Reset() {
    requests.Reset();
    replies.Reset();
    req_errors.Reset();
    batches.Reset();
    batched_requests.Reset();
    batched_rows.Reset();
    bucket_miss.Reset();
    full_flushes.Reset();
    deadline_flushes.Reset();
    bytes_in.Reset();
    bytes_out.Reset();
    err_frames.Reset();
    proto_errors.Reset();
    handshake_fails.Reset();
    conns_accepted.Reset();
    queue_depth.Reset();
    batch_fill.Reset();
    e2e_us.Reset();
    run_us.Reset();
  }
};

/* Dynamic micro-batcher: a bounded FIFO request queue drained by N
 * instance workers. A worker flushes when `max_batch` rows are queued
 * or `deadline_us` has elapsed since the OLDEST queued request —
 * batch-1 latency under light load never exceeds the deadline, and
 * under heavy load batches fill before the timer matters. Whole
 * requests only (no splitting), strictly FIFO, so de-muxed replies
 * preserve per-connection submission order. The runner is injected:
 * the server hands the stitched batch to a predictor instance; the
 * selftest injects a recording fake. */
class SvBatcher {
 public:
  using Runner = std::function<void(int instance,
                                    std::vector<SvRequest>& batch)>;

  SvBatcher(int64_t max_batch, int64_t deadline_us, int instances,
            SvStats* stats, Runner runner)
      : max_batch_(max_batch),
        deadline_us_(deadline_us),
        max_queue_rows_(std::max<int64_t>(64, 16 * max_batch)),
        stats_(stats),
        runner_(std::move(runner)) {
    for (int i = 0; i < instances; ++i)
      workers_.emplace_back([this, i] { worker(i); });
  }

  ~SvBatcher() { stop(); }

  bool enqueue(SvRequest&& r, std::string* why) {
    std::unique_lock<std::mutex> l(mu_);
    if (stop_) {
      if (why) *why = "server stopping";
      return false;
    }
    if (r.rows < 1 || r.rows > max_batch_) {
      if (why)
        *why = "request rows " + std::to_string(r.rows) +
               " outside [1, max_batch=" + std::to_string(max_batch_) +
               "]";
      return false;
    }
    if (rows_queued_ + r.rows > max_queue_rows_) {
      // bounded backpressure: a flood of producers must not grow the
      // queue (and its payload copies) without limit
      if (why) *why = "request queue full";
      return false;
    }
    rows_queued_ += r.rows;
    q_.push_back(std::move(r));
    stats_->queue_depth.Observe(uint64_t(q_.size()));
    cv_.notify_one();
    return true;
  }

  // stop workers; remaining queued requests are returned to the
  // caller (the server errors them out before closing connections)
  std::deque<SvRequest> stop() {
    {
      std::lock_guard<std::mutex> l(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& t : workers_)
      if (t.joinable()) t.join();
    workers_.clear();
    std::lock_guard<std::mutex> l(mu_);
    rows_queued_ = 0;
    return std::move(q_);
  }

  int64_t queued_rows() const {
    std::lock_guard<std::mutex> l(mu_);
    return rows_queued_;
  }

 private:
  void worker(int instance) {
    std::unique_lock<std::mutex> l(mu_);
    for (;;) {
      cv_.wait(l, [&] { return stop_ || !q_.empty(); });
      if (q_.empty()) {
        if (stop_) return;
        continue;
      }
      // wait for the batch to fill, but never past the oldest
      // request's deadline
      const int64_t deadline = q_.front().t_enq_us + deadline_us_;
      while (!stop_ && rows_queued_ < max_batch_) {
        const int64_t now = ptpu::NowUs();
        if (now >= deadline) break;
        ptpu::CvWaitForUs(cv_, l, deadline - now);
        if (q_.empty()) break;  // another instance drained it
      }
      if (q_.empty()) {
        if (stop_) return;
        continue;
      }
      std::vector<SvRequest> batch;
      int64_t rows = 0;
      while (!q_.empty() && rows + q_.front().rows <= max_batch_) {
        rows += q_.front().rows;
        batch.push_back(std::move(q_.front()));
        q_.pop_front();
      }
      rows_queued_ -= rows;
      (rows >= max_batch_ ? stats_->full_flushes
                          : stats_->deadline_flushes)
          .Add(1);
      stats_->batches.Add(1);
      stats_->batched_requests.Add(batch.size());
      stats_->batched_rows.Add(uint64_t(rows));
      stats_->batch_fill.Observe(uint64_t(rows));
      if (!q_.empty()) cv_.notify_one();  // more work for a sibling
      l.unlock();
      runner_(instance, batch);
      l.lock();
    }
  }

  const int64_t max_batch_, deadline_us_, max_queue_rows_;
  SvStats* stats_;
  Runner runner_;
  mutable std::mutex mu_;
  std::condition_variable cv_;
  std::deque<SvRequest> q_;
  int64_t rows_queued_ = 0;
  bool stop_ = false;
  std::vector<std::thread> workers_;
};

// model input signature, captured once from the bucket-1 predictor
struct SvInputSig {
  std::string name;
  int dtype = SV_F32;
  std::vector<int64_t> tail;  // dims past the batch axis
  int64_t row_elems = 1;
};

struct SvInstance {
  void* pool = nullptr;                       // ptpu_workpool handle
  std::map<int64_t, PTPU_Predictor*> buckets;  // batch size -> handle
  std::vector<std::vector<uint8_t>> stage;     // per-input batch bufs

  ~SvInstance() {
    for (auto& kv : buckets) ptpu_predictor_destroy(kv.second);
    if (pool) ptpu_workpool_destroy(pool);
  }
};

struct SvServer {
  std::string model_path;
  std::string authkey;
  int listen_fd = -1;
  int port = 0;
  int64_t max_batch = 8;
  int64_t deadline_us = 2000;
  int instances = 2;
  int threads_per_instance = 0;
  std::vector<int64_t> ladder;
  std::vector<SvInputSig> sig;
  int n_outputs = 0;
  std::string meta_json;

  std::vector<std::unique_ptr<SvInstance>> insts;
  std::unique_ptr<SvBatcher> batcher;
  SvStats stats;

  std::atomic<bool> stop{false};
  std::thread accept_thread;
  std::mutex conn_mu;
  std::vector<std::shared_ptr<SvConn>> conns;
  std::vector<std::thread> conn_threads;
  std::vector<std::thread::id> done_threads;

  ~SvServer() { Stop(); }

  // ---------------------------------------------------------- start
  // throws std::runtime_error on any setup failure
  void Start(int want_port, int loopback_only) {
    char err[512] = {0};
    // bucket ladder: {1, 2, 4, ..., max_batch}; each predictor is
    // re-planned for its bucket so batched runs stay zero-alloc
    for (int64_t b = 1; b < max_batch; b *= 2) ladder.push_back(b);
    ladder.push_back(max_batch);

    const int hw = [] {
      const char* e = std::getenv("PTPU_PREDICTOR_THREADS");
      int v = e ? std::atoi(e) : 0;
      if (v <= 0) v = int(std::thread::hardware_concurrency());
      return std::max(1, v);
    }();
    if (threads_per_instance <= 0)
      threads_per_instance = std::max(1, hw / std::max(1, instances));

    for (int i = 0; i < instances; ++i) {
      auto inst = std::unique_ptr<SvInstance>(new SvInstance());
      inst->pool = ptpu_workpool_create(threads_per_instance);
      for (int64_t b : ladder) {
        PTPU_Predictor* p = ptpu_predictor_create_opts(
            model_path.c_str(), b, 0, err, sizeof(err));
        if (!p)
          throw std::runtime_error(std::string("bucket ") +
                                   std::to_string(b) + ": " + err);
        ptpu_predictor_set_pool(p, inst->pool);
        inst->buckets[b] = p;
      }
      insts.push_back(std::move(inst));
    }

    // input signature from the bucket-1 predictor (tail dims shared
    // by every bucket; the batch axis is the override)
    PTPU_Predictor* p1 = insts[0]->buckets[1];
    const int nin = ptpu_predictor_num_inputs(p1);
    if (nin <= 0) throw std::runtime_error("model has no inputs");
    for (int i = 0; i < nin; ++i) {
      SvInputSig s;
      s.name = ptpu_predictor_input_name(p1, i);
      s.dtype = ptpu_predictor_input_dtype(p1, i);
      if (s.dtype == 11) s.dtype = SV_F32;  // f64 parses as f32
      if (sv_dtype_size(s.dtype) == 0)
        throw std::runtime_error("input '" + s.name +
                                 "' has unsupported dtype " +
                                 std::to_string(s.dtype));
      const int nd = ptpu_predictor_input_ndim(p1, i);
      const int64_t* d = ptpu_predictor_input_dims(p1, i);
      if (nd < 1 || !d)
        throw std::runtime_error("input '" + s.name +
                                 "' needs a batch axis to serve");
      for (int k = 1; k < nd; ++k) {
        if (d[k] <= 0)
          throw std::runtime_error("input '" + s.name +
                                   "' has dynamic dims");
        s.tail.push_back(d[k]);
        s.row_elems *= d[k];
      }
      sig.push_back(std::move(s));
    }
    n_outputs = ptpu_predictor_num_outputs(p1);

    /* Probe every bucket with a zero batch once: a graph that is not
     * batch-polymorphic (static Reshape constants baked to the export
     * batch) fails HERE, at load, not on the first live batch. Failed
     * buckets > 1 are dropped and max_batch capped to the largest
     * surviving bucket; a failing bucket 1 fails start. */
    std::vector<int64_t> ok_ladder;
    for (int64_t b : ladder) {
      std::string perr;
      if (ProbeBucket(b, &perr)) {
        ok_ladder.push_back(b);
      } else if (b == 1) {
        throw std::runtime_error("bucket-1 probe failed: " + perr);
      } else {
        for (auto& inst : insts) {
          ptpu_predictor_destroy(inst->buckets[b]);
          inst->buckets.erase(b);
        }
      }
    }
    ladder = ok_ladder;
    max_batch = ladder.back();

    for (auto& inst : insts) inst->stage.resize(sig.size());

    BuildMetaJson();

    batcher.reset(new SvBatcher(
        max_batch, deadline_us, instances, &stats,
        [this](int instance, std::vector<SvRequest>& batch) {
          RunBatch(instance, batch);
        }));

    listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd < 0) throw std::runtime_error("socket() failed");
    const int one = 1;
    ::setsockopt(listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr =
        htonl(loopback_only ? INADDR_LOOPBACK : INADDR_ANY);
    addr.sin_port = htons(uint16_t(want_port));
    if (::bind(listen_fd, reinterpret_cast<sockaddr*>(&addr),
               sizeof(addr)) != 0 ||
        ::listen(listen_fd, 128) != 0)
      throw std::runtime_error("bind/listen on port " +
                               std::to_string(want_port) + " failed");
    socklen_t alen = sizeof(addr);
    ::getsockname(listen_fd, reinterpret_cast<sockaddr*>(&addr), &alen);
    port = int(ntohs(addr.sin_port));
    accept_thread = std::thread([this] { AcceptLoop(); });
  }

  bool ProbeBucket(int64_t b, std::string* perr) {
    char err[512] = {0};
    for (auto& inst : insts) {
      PTPU_Predictor* p = inst->buckets[b];
      for (size_t i = 0; i < sig.size(); ++i) {
        std::vector<int64_t> dims;
        dims.push_back(b);
        dims.insert(dims.end(), sig[i].tail.begin(), sig[i].tail.end());
        const int64_t n = b * sig[i].row_elems;
        int rc;
        if (sig[i].dtype == SV_F32) {
          std::vector<float> z(size_t(n), 0.f);
          rc = ptpu_predictor_set_input(p, sig[i].name.c_str(), z.data(),
                                        dims.data(), int(dims.size()),
                                        err, sizeof(err));
        } else if (sig[i].dtype == SV_I32) {
          std::vector<int32_t> z(size_t(n), 0);
          rc = ptpu_predictor_set_input_i32(p, sig[i].name.c_str(),
                                            z.data(), dims.data(),
                                            int(dims.size()), err,
                                            sizeof(err));
        } else {
          std::vector<int64_t> z(size_t(n), 0);
          rc = ptpu_predictor_set_input_i64(p, sig[i].name.c_str(),
                                            z.data(), dims.data(),
                                            int(dims.size()), err,
                                            sizeof(err));
        }
        if (rc != 0) {
          *perr = err;
          return false;
        }
      }
      if (ptpu_predictor_run(p, err, sizeof(err)) != 0) {
        *perr = err;
        return false;
      }
      // every output must carry the batch on axis 0 or de-muxing
      // replies row-wise would hand clients other requests' data
      for (int o = 0; o < n_outputs; ++o) {
        const int nd = ptpu_predictor_output_ndim(p, o);
        const int64_t* od = ptpu_predictor_output_dims(p, o);
        if (nd < 1 || !od || od[0] != b) {
          *perr = "output " + std::to_string(o) +
                  " does not carry the batch on axis 0";
          return false;
        }
      }
    }
    return true;
  }

  void BuildMetaJson() {
    std::string out = "{\"version\":1,";
    ptpu::AppendJsonU64(&out, "max_batch", uint64_t(max_batch));
    out += ',';
    ptpu::AppendJsonU64(&out, "deadline_us", uint64_t(deadline_us));
    out += ',';
    ptpu::AppendJsonU64(&out, "instances", uint64_t(instances));
    out += ',';
    ptpu::AppendJsonU64(&out, "threads_per_instance",
                        uint64_t(threads_per_instance));
    out += ",\"buckets\":[";
    for (size_t k = 0; k < ladder.size(); ++k) {
      if (k) out += ',';
      out += std::to_string(ladder[k]);
    }
    out += "],";
    ptpu::AppendJsonU64(&out, "n_outputs", uint64_t(n_outputs));
    out += ",\"inputs\":[";
    for (size_t i = 0; i < sig.size(); ++i) {
      if (i) out += ',';
      out += "{\"name\":\"" + ptpu::JsonEscape(sig[i].name) + "\",";
      ptpu::AppendJsonU64(&out, "dtype", uint64_t(sig[i].dtype));
      out += ",\"tail_dims\":[";
      for (size_t k = 0; k < sig[i].tail.size(); ++k) {
        if (k) out += ',';
        out += std::to_string(sig[i].tail[k]);
      }
      out += "]}";
    }
    out += "]}";
    meta_json = std::move(out);
  }

  // ------------------------------------------------------ batch run
  void SendErrFrame(const std::shared_ptr<SvConn>& conn, uint64_t id,
                    const std::string& msg) {
    std::vector<uint8_t> f(4 + 2 + 8 + 4 + msg.size());
    PutU32(f.data(), uint32_t(f.size() - 4));
    f[4] = kSvWireVersion;
    f[5] = kTagInferErr;
    std::memcpy(f.data() + 6, &id, 8);
    PutU32(f.data() + 14, uint32_t(msg.size()));
    std::memcpy(f.data() + 18, msg.data(), msg.size());
    stats.err_frames.Add(1);
    stats.req_errors.Add(1);
    stats.bytes_out.Add(f.size());
    conn->Send(f);
  }

  void RunBatch(int instance, std::vector<SvRequest>& batch) {
    SvInstance& inst = *insts[size_t(instance)];
    int64_t rows = 0;
    for (const auto& r : batch) rows += r.rows;
    // smallest bucket that fits; pad rows up to it (zero rows — their
    // outputs are computed and discarded, which keeps the run on the
    // bucket's pre-planned arena instead of falling off-plan)
    int64_t bucket = ladder.back();
    for (int64_t b : ladder)
      if (b >= rows) {
        bucket = b;
        break;
      }
    if (bucket != rows) stats.bucket_miss.Add(1);
    PTPU_Predictor* p = inst.buckets[bucket];

    char err[512] = {0};
    const auto fail_all = [&](const std::string& msg) {
      for (auto& r : batch) SendErrFrame(r.conn, r.id, msg);
    };

    for (size_t i = 0; i < sig.size(); ++i) {
      const size_t esz = size_t(sv_dtype_size(sig[i].dtype));
      const size_t row_b = size_t(sig[i].row_elems) * esz;
      auto& buf = inst.stage[i];
      const size_t need = size_t(bucket) * row_b;
      if (buf.size() < need) buf.resize(need);
      size_t off = 0;
      for (const auto& r : batch) {
        std::memcpy(buf.data() + off, r.inputs[i].data.data(),
                    r.inputs[i].data.size());
        off += r.inputs[i].data.size();
      }
      if (off < need) std::memset(buf.data() + off, 0, need - off);
      std::vector<int64_t> dims;
      dims.push_back(bucket);
      dims.insert(dims.end(), sig[i].tail.begin(), sig[i].tail.end());
      int rc;
      if (sig[i].dtype == SV_F32)
        rc = ptpu_predictor_set_input(
            p, sig[i].name.c_str(),
            reinterpret_cast<const float*>(buf.data()), dims.data(),
            int(dims.size()), err, sizeof(err));
      else if (sig[i].dtype == SV_I32)
        rc = ptpu_predictor_set_input_i32(
            p, sig[i].name.c_str(),
            reinterpret_cast<const int32_t*>(buf.data()), dims.data(),
            int(dims.size()), err, sizeof(err));
      else
        rc = ptpu_predictor_set_input_i64(
            p, sig[i].name.c_str(),
            reinterpret_cast<const int64_t*>(buf.data()), dims.data(),
            int(dims.size()), err, sizeof(err));
      if (rc != 0) return fail_all(std::string("set_input: ") + err);
    }

    const int64_t t0 = ptpu::NowUs();
    if (ptpu_predictor_run(p, err, sizeof(err)) != 0)
      return fail_all(std::string("run: ") + err);
    stats.run_us.Observe(uint64_t(ptpu::NowUs() - t0));

    // de-mux row-wise, FIFO: request k gets rows [row_off, row_off +
    // rows_k) of every output
    struct OutView {
      const float* data;
      std::vector<int64_t> dims;
      int64_t row_elems;
    };
    std::vector<OutView> outs;
    for (int o = 0; o < n_outputs; ++o) {
      OutView v;
      const int nd = ptpu_predictor_output_ndim(p, o);
      const int64_t* od = ptpu_predictor_output_dims(p, o);
      v.data = ptpu_predictor_output_data(p, o);
      if (nd < 1 || !od || !v.data || od[0] != bucket)
        return fail_all("output " + std::to_string(o) +
                        " lost the batch axis");
      v.dims.assign(od, od + nd);
      v.row_elems = 1;
      for (int k = 1; k < nd; ++k) v.row_elems *= od[k];
      outs.push_back(std::move(v));
    }

    int64_t row_off = 0;
    for (auto& r : batch) {
      // frame: [len][ver][tag][id][u16 n_outputs] + outputs
      size_t fsz = 4 + 2 + 8 + 2;
      for (const auto& v : outs)
        fsz += 1 + v.dims.size() * 8 +
               size_t(r.rows) * size_t(v.row_elems) * 4;
      std::vector<uint8_t> f(fsz);
      PutU32(f.data(), uint32_t(fsz - 4));
      f[4] = kSvWireVersion;
      f[5] = kTagInferRep;
      std::memcpy(f.data() + 6, &r.id, 8);
      const uint16_t no16 = uint16_t(n_outputs);
      std::memcpy(f.data() + 14, &no16, 2);
      size_t off = 16;
      for (const auto& v : outs) {
        f[off++] = uint8_t(v.dims.size());
        int64_t d0 = r.rows;
        std::memcpy(f.data() + off, &d0, 8);
        off += 8;
        for (size_t k = 1; k < v.dims.size(); ++k) {
          std::memcpy(f.data() + off, &v.dims[k], 8);
          off += 8;
        }
        const size_t nb = size_t(r.rows) * size_t(v.row_elems) * 4;
        std::memcpy(f.data() + off, v.data + row_off * v.row_elems, nb);
        off += nb;
      }
      row_off += r.rows;
      if (r.conn->Send(f)) {
        stats.replies.Add(1);
        stats.bytes_out.Add(f.size());
        stats.e2e_us.Observe(uint64_t(ptpu::NowUs() - r.t_enq_us));
      }
    }
  }

  // ------------------------------------------------------ wire loop

  void Serve(const std::shared_ptr<SvConn>& conn) {
    const int fd = conn->fd;
    if (!ptpu::ServerHandshake(fd, authkey)) {
      stats.handshake_fails.Add(1);
      return;
    }
    std::vector<uint8_t> req;
    const auto proto_err = [this] { stats.proto_errors.Add(1); };
    for (;;) {
      uint8_t lenb[4];
      if (!ReadExact(fd, lenb, 4)) return;
      const uint32_t n = GetU32(lenb);
      if (n < 2 || n > kSvMaxFrame) return proto_err();
      if (req.size() < n) req.resize(n);
      if (!ReadExact(fd, req.data(), n)) return;
      stats.bytes_in.Add(4 + uint64_t(n));
      if (req[0] != kSvWireVersion) return proto_err();
      const uint8_t tag = req[1];
      if (tag == kTagMetaReq) {
        std::vector<uint8_t> f(4 + 2 + 4 + meta_json.size());
        PutU32(f.data(), uint32_t(f.size() - 4));
        f[4] = kSvWireVersion;
        f[5] = kTagMetaRep;
        PutU32(f.data() + 6, uint32_t(meta_json.size()));
        std::memcpy(f.data() + 10, meta_json.data(), meta_json.size());
        stats.bytes_out.Add(f.size());
        if (!conn->Send(f)) return;
        continue;
      }
      if (tag != kTagInferReq) return proto_err();
      // [u64 req_id][u16 n_inputs] per input:
      // [u8 dtype][u8 ndim][ndim x i64][raw]
      if (n < 2 + 8 + 2) return proto_err();
      SvRequest r;
      std::memcpy(&r.id, req.data() + 2, 8);
      uint16_t nin;
      std::memcpy(&nin, req.data() + 10, 2);
      size_t off = 12;
      std::string bad;
      if (nin != sig.size())
        bad = "expected " + std::to_string(sig.size()) +
              " inputs, got " + std::to_string(nin);
      r.inputs.resize(sig.size());
      int64_t rows = -1;
      for (size_t i = 0; bad.empty() && i < sig.size(); ++i) {
        if (n < off + 2) return proto_err();
        const int dt = req[off];
        const int nd = req[off + 1];
        off += 2;
        if (nd < 1 || nd > kSvMaxNdim || n < off + size_t(nd) * 8)
          return proto_err();
        SvInput& in = r.inputs[i];
        in.dtype = dt;
        in.dims.resize(size_t(nd));
        std::memcpy(in.dims.data(), req.data() + off, size_t(nd) * 8);
        off += size_t(nd) * 8;
        if (dt != sig[i].dtype) {
          bad = "input '" + sig[i].name + "': dtype " +
                std::to_string(dt) + " != model dtype " +
                std::to_string(sig[i].dtype);
          break;
        }
        if (size_t(nd) != sig[i].tail.size() + 1) {
          bad = "input '" + sig[i].name + "': ndim " +
                std::to_string(nd) + " != " +
                std::to_string(sig[i].tail.size() + 1);
          break;
        }
        for (size_t k = 0; k < sig[i].tail.size(); ++k)
          if (in.dims[k + 1] != sig[i].tail[k]) {
            bad = "input '" + sig[i].name +
                  "': non-batch dims do not match the model";
            break;
          }
        if (!bad.empty()) break;
        if (in.dims[0] < 1) {
          bad = "input '" + sig[i].name + "': batch dim must be >= 1";
          break;
        }
        if (rows < 0) rows = in.dims[0];
        else if (in.dims[0] != rows) {
          bad = "inputs disagree on the batch dim";
          break;
        }
        const size_t nb = size_t(in.dims[0]) *
                          size_t(sig[i].row_elems) *
                          size_t(sv_dtype_size(sig[i].dtype));
        if (n < off + nb) return proto_err();
        in.data.assign(req.data() + off, req.data() + off + nb);
        off += nb;
      }
      stats.requests.Add(1);
      if (!bad.empty()) {
        SendErrFrame(conn, r.id, bad);
        continue;
      }
      r.rows = rows;
      r.conn = conn;
      r.t_enq_us = ptpu::NowUs();
      // backpressure: retry briefly before refusing — closed-loop
      // clients outrunning the instances see latency, not errors.
      // enqueue only moves the request on success, so r stays intact
      // across failed attempts; id/conn are saved for the error path.
      std::string why;
      const uint64_t rid = r.id;
      bool okq = false;
      for (int attempt = 0; attempt < 200; ++attempt) {
        okq = batcher->enqueue(std::move(r), &why);
        if (okq || why != "request queue full") break;
        std::this_thread::sleep_for(std::chrono::microseconds(500));
      }
      if (!okq) SendErrFrame(conn, rid, why);
    }
  }

  void ReapFinished() {
    std::vector<std::thread> reap;
    {
      std::lock_guard<std::mutex> g(conn_mu);
      if (done_threads.empty()) return;
      for (auto it = conn_threads.begin(); it != conn_threads.end();) {
        if (std::find(done_threads.begin(), done_threads.end(),
                      it->get_id()) != done_threads.end()) {
          reap.push_back(std::move(*it));
          it = conn_threads.erase(it);
        } else {
          ++it;
        }
      }
      done_threads.clear();
    }
    for (auto& t : reap)
      if (t.joinable()) t.join();
  }

  void AcceptLoop() {
    for (;;) {
      const int fd = ::accept(listen_fd, nullptr, nullptr);
      if (fd < 0) {
        // a transient accept failure (peer RST, EINTR, momentary fd
        // exhaustion) must not permanently stop the server from
        // accepting; only the Stop()-closed listener ends the loop
        if (!stop.load() && ptpu::AcceptErrnoIsTransient(errno)) {
          std::this_thread::sleep_for(std::chrono::milliseconds(5));
          continue;
        }
        return;
      }
      if (stop.load()) {
        ::close(fd);
        return;
      }
      ReapFinished();
      stats.conns_accepted.Add(1);
      const int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      const int buf = 4 << 20;
      ::setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &buf, sizeof(buf));
      ::setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &buf, sizeof(buf));
      // bound reply writes: a client that stops READING replies would
      // otherwise block an instance worker inside Send forever once
      // its 4MB send buffer fills (and hang Stop with it)
      struct timeval tv{10, 0};
      ::setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
      auto conn = std::make_shared<SvConn>();
      conn->fd = fd;
      std::lock_guard<std::mutex> g(conn_mu);
      conns.push_back(conn);
      conn_threads.emplace_back([this, conn] {
        stats.conns_active.fetch_add(1, std::memory_order_relaxed);
        try {
          Serve(conn);
        } catch (...) {
        }
        stats.conns_active.fetch_sub(1, std::memory_order_relaxed);
        conn->Close();
        {
          std::lock_guard<std::mutex> g2(conn_mu);
          conns.erase(std::remove(conns.begin(), conns.end(), conn),
                      conns.end());
          done_threads.push_back(std::this_thread::get_id());
        }
        ::close(conn->fd);
      });
    }
  }

  void Stop() {
    if (stop.exchange(true)) return;
    // shutdown() wakes the blocked accept() (EINVAL) but keeps the fd
    // alive; closing or clearing listen_fd BEFORE the join would race
    // the accept thread's concurrent read of it (TSan-caught) and
    // invite fd-number reuse while accept() still holds the old value
    if (listen_fd >= 0) ::shutdown(listen_fd, SHUT_RDWR);
    if (accept_thread.joinable()) accept_thread.join();
    if (listen_fd >= 0) {
      ::close(listen_fd);
      listen_fd = -1;
    }
    // stop the batcher FIRST (in-flight batches reply over still-open
    // conns, leftover queued requests get explicit errors) but keep
    // the OBJECT alive until the conn reader threads are joined —
    // they may still call enqueue(), which answers "server stopping"
    // on a stopped batcher but would be UB on a destroyed one
    std::deque<SvRequest> leftover;
    if (batcher) leftover = batcher->stop();
    for (auto& r : leftover)
      SendErrFrame(r.conn, r.id, "server stopping");
    {
      std::lock_guard<std::mutex> g(conn_mu);
      for (auto& c : conns) c->Close();
    }
    std::vector<std::thread> ts;
    {
      std::lock_guard<std::mutex> g(conn_mu);
      ts.swap(conn_threads);
      done_threads.clear();
    }
    for (auto& t : ts)
      if (t.joinable()) t.join();
    batcher.reset();
  }

  // --------------------------------------------------------- stats
  std::string StatsJson() {
    std::string out = "{\"server\":{";
    const struct {
      const char* name;
      const ptpu::Counter* c;
    } cs[] = {
        {"requests", &stats.requests},
        {"replies", &stats.replies},
        {"req_errors", &stats.req_errors},
        {"err_frames", &stats.err_frames},
        {"proto_errors", &stats.proto_errors},
        {"handshake_fails", &stats.handshake_fails},
        {"conns_accepted", &stats.conns_accepted},
        {"bytes_in", &stats.bytes_in},
        {"bytes_out", &stats.bytes_out},
    };
    for (const auto& kv : cs) {
      ptpu::AppendJsonU64(&out, kv.name, kv.c->Get());
      out += ',';
    }
    ptpu::AppendJsonU64(
        &out, "conns_active",
        uint64_t(stats.conns_active.load(std::memory_order_relaxed)));
    out += "},\"batcher\":{";
    const struct {
      const char* name;
      const ptpu::Counter* c;
    } bs[] = {
        {"batches", &stats.batches},
        {"batched_requests", &stats.batched_requests},
        {"batched_rows", &stats.batched_rows},
        {"bucket_miss", &stats.bucket_miss},
        {"full_flushes", &stats.full_flushes},
        {"deadline_flushes", &stats.deadline_flushes},
    };
    for (const auto& kv : bs) {
      ptpu::AppendJsonU64(&out, kv.name, kv.c->Get());
      out += ',';
    }
    // bucket-ladder coverage: runs that fell off a planned arena,
    // summed over every instance's bucket predictors (delta since the
    // last stats_reset — see dyn_fallback_base_)
    const uint64_t dyn = DynFallbackSum();
    const uint64_t base =
        dyn_fallback_base_.load(std::memory_order_relaxed);
    ptpu::AppendJsonU64(&out, "dynamic_shape_fallback",
                        dyn > base ? dyn - base : 0);
    out += ',';
    ptpu::AppendJsonHist(&out, "queue_depth", stats.queue_depth);
    out += ',';
    ptpu::AppendJsonHist(&out, "batch_fill", stats.batch_fill);
    out += ',';
    ptpu::AppendJsonHist(&out, "e2e_us", stats.e2e_us);
    out += ',';
    ptpu::AppendJsonHist(&out, "run_us", stats.run_us);
    out += "}}";
    return out;
  }

  uint64_t DynFallbackSum() const {
    uint64_t dyn = 0;
    for (const auto& inst : insts)
      for (const auto& kv : inst->buckets)
        dyn += uint64_t(ptpu_predictor_dynamic_fallbacks(kv.second));
    return dyn;
  }

  /* Reset zeroes the serving counters only. The bucket predictors'
   * own stats are NOT reset — an instance worker may be mid-run, and
   * ptpu_predictor_stats_reset rebuilds structures run() is holding
   * pointers into (the predictor is thread-compatible, not
   * thread-safe). dynamic_shape_fallback instead resets by baseline
   * subtraction against the predictors' monotonic atomic counters. */
  std::atomic<uint64_t> dyn_fallback_base_{0};

  void StatsReset() {
    stats.Reset();
    dyn_fallback_base_.store(DynFallbackSum(),
                             std::memory_order_relaxed);
  }
};

thread_local std::string g_sv_json;

}  // namespace

extern "C" {

__attribute__((visibility("default")))
void* ptpu_serving_start(const char* model_path, int port,
                         const char* authkey, int authkey_len,
                         int max_batch, int64_t deadline_us,
                         int instances, int threads_per_instance,
                         int loopback_only, char* err, int err_len) {
  auto* s = new SvServer();
  try {
    s->model_path = model_path ? model_path : "";
    s->authkey.assign(authkey ? authkey : "",
                      authkey_len > 0 ? size_t(authkey_len) : 0);
    s->max_batch = max_batch > 0 ? max_batch : 8;
    s->deadline_us = deadline_us > 0 ? deadline_us : 2000;
    s->instances = instances > 0 ? instances : 2;
    s->threads_per_instance = threads_per_instance;
    s->Start(port, loopback_only);
    return s;
  } catch (const std::exception& e) {
    if (err && err_len > 0)
      std::snprintf(err, size_t(err_len), "%s", e.what());
    delete s;
    return nullptr;
  }
}

// Handle-taking entries guard NULL (a failed start returns NULL; a
// binding must be able to pass that back without a segfault).
__attribute__((visibility("default")))
int ptpu_serving_port(void* h) {
  auto* s = static_cast<SvServer*>(h);
  return s ? s->port : -1;
}

__attribute__((visibility("default")))
const char* ptpu_serving_config_json(void* h) {
  auto* s = static_cast<SvServer*>(h);
  if (!s) return "{}";
  g_sv_json = s->meta_json;
  return g_sv_json.c_str();
}

__attribute__((visibility("default")))
const char* ptpu_serving_stats_json(void* h) {
  auto* s = static_cast<SvServer*>(h);
  if (!s) return "{}";
  g_sv_json = s->StatsJson();
  return g_sv_json.c_str();
}

__attribute__((visibility("default")))
void ptpu_serving_stats_reset(void* h) {
  auto* s = static_cast<SvServer*>(h);
  if (!s) return;
  s->StatsReset();
}

__attribute__((visibility("default")))
void ptpu_serving_stop(void* h) {
  auto* s = static_cast<SvServer*>(h);
  if (!s) return;
  s->Stop();
  delete s;
}

}  // extern "C"
