// Seeded historical-bug fixture: the r10 eventfd lost wakeup.
//
// The net core's Run() loop must clear the wake eventfd BEFORE
// swapping the inbox (see the comment block in csrc/ptpu_net.cc). The
// original r10 code swapped first: a task posted into the
// swap-to-clear window had its eventfd signal consumed while the task
// itself stayed stranded in the inbox, and the loop then blocked
// forever in epoll_wait — the selftest hung on ~50% of runs until the
// schedule happened to fire. This fixture reintroduces the buggy
// ordering as a MODEL (BlockUntil = epoll_wait on the eventfd) and
// asserts that ptpu_schedck
//   1. rediscovers the hang within a bounded schedule budget, under
//      BOTH strategies (dfs exhaustively, pct probabilistically),
//   2. replays it from the recorded decision trace on the FIRST
//      schedule, with a byte-identical report, and
//   3. passes the FIXED clear-then-swap protocol exhaustively clean
//      (the negative control — mirroring the lockdep fixture
//      pattern).
//
// Built only by the schedck targets (-DPTPU_SCHEDCK -DPTPU_LOCKDEP);
// runs in `make selftest`, both sancheck legs and the run_checks
// schedck leg.
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

#include <atomic>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include "ptpu_schedck.h"
#include "ptpu_sync.h"

namespace sck = ptpu::schedck;

// same name + rank as the production inbox class (csrc/ptpu_net.h)
PTPU_LOCK_CLASS(kClsNetInbox, "net.inbox", 110);

namespace {

constexpr uint64_t kBudget = 5000;  // discovery budget, both legs
const char* kTracePath = "ptpu_schedck_fixture_lostwake.trace";

int g_tests = 0;

void ok(const char* name) {
  ++g_tests;
  std::printf("ok %2d - %s\n", g_tests, name);
  std::fflush(stdout);
}

void fail(const char* why, const std::string& detail) {
  std::fprintf(stderr, "FAIL lostwake fixture: %s\n%s\n", why,
               detail.c_str());
  std::exit(1);
}

// The event-loop model. `clear_before_swap` selects the FIXED (true)
// or the seeded r10 buggy (false) ordering.
void EventLoopRound(bool clear_before_swap) {
  struct St {
    ptpu::Mutex mu{kClsNetInbox};
    std::vector<int> inbox;
    std::atomic<int> efd{0};
    int drained = 0;
  } st;
  constexpr int kTasks = 2;
  sck::Thread loop([&st, clear_before_swap] {
    while (st.drained < kTasks) {
      // epoll_wait on the wake eventfd
      sck::BlockUntil([&st] { return st.efd.load() != 0; },
                      "epoll_wait(wake eventfd)");
      std::vector<int> tasks;
      if (clear_before_swap) {
        st.efd.store(0);     // clear FIRST (the r10 fix): a post
        PTPU_SCHED_POINT();  // landing here re-signals the eventfd
        ptpu::MutexLock g(st.mu);
        tasks.swap(st.inbox);
      } else {
        {  // r10 bug: swap FIRST...
          ptpu::MutexLock g(st.mu);
          tasks.swap(st.inbox);
        }
        PTPU_SCHED_POINT();  // ...a post lands here, stranded...
        st.efd.store(0);     // ...and its signal is consumed
      }
      st.drained += int(tasks.size());
    }
  });
  sck::Thread poster([&st] {
    for (int i = 0; i < kTasks; ++i) {
      {
        ptpu::MutexLock g(st.mu);
        st.inbox.push_back(i);
      }
      PTPU_SCHED_POINT();  // queued, eventfd not yet written
      st.efd.store(1);
    }
  });
  poster.join();
  loop.join();  // the lost wakeup deadlocks exactly here
}

void BuggyBody() { EventLoopRound(false); }
void FixedBody() { EventLoopRound(true); }

void ChildDiscoverDfs() {
  sck::Options o;
  o.strategy = sck::Options::Strategy::kDfs;
  o.max_schedules = kBudget;
  o.depth = 10;
  o.trace_out = kTracePath;
  sck::Explore("lostwake_buggy", BuggyBody, o);
}

void ChildDiscoverPct() {
  sck::Options o;
  o.strategy = sck::Options::Strategy::kPct;
  o.max_schedules = kBudget;
  o.depth = 3;
  o.seed = 1;
  o.trace_out = kTracePath;
  sck::Explore("lostwake_buggy", BuggyBody, o);
}

void ChildReplay() {
  sck::Replay("lostwake_buggy", BuggyBody, kTracePath);
}

// Fork `fn`; expect SIGABRT; return the child's stderr.
std::string RunDeathTest(void (*fn)()) {
  int fds[2];
  if (pipe(fds) != 0) fail("pipe failed", "");
  const pid_t pid = fork();
  if (pid < 0) fail("fork failed", "");
  if (pid == 0) {
    close(fds[0]);
    dup2(fds[1], 2);
    close(fds[1]);
    fn();
    _exit(0);  // no failure found == fixture bug not rediscovered
  }
  close(fds[1]);
  std::string err;
  char buf[4096];
  ssize_t n;
  while ((n = read(fds[0], buf, sizeof(buf))) > 0)
    err.append(buf, size_t(n));
  close(fds[0]);
  int wst = 0;
  waitpid(pid, &wst, 0);
  if (!WIFSIGNALED(wst) || WTERMSIG(wst) != SIGABRT)
    fail("expected SIGABRT (bug not rediscovered in budget)", err);
  return err;
}

uint64_t ParseSchedule(const std::string& report) {
  const size_t p = report.find("schedule ");
  if (p == std::string::npos) fail("no schedule in report", report);
  return std::strtoull(report.c_str() + p + 9, nullptr, 10);
}

void CheckDiscovery(void (*child)(), const char* what) {
  std::remove(kTracePath);
  const std::string rep = RunDeathTest(child);
  if (rep.find("DEADLOCK") == std::string::npos)
    fail("expected a DEADLOCK report", rep);
  FILE* f = std::fopen(kTracePath, "r");
  if (!f) fail("no decision trace written", rep);
  std::fclose(f);
  const uint64_t k = ParseSchedule(rep);
  if (k >= kBudget) fail("discovery outside budget", rep);
  std::printf("ok %2d - %s rediscovered the r10 lost wakeup at "
              "schedule %llu (budget %llu)\n",
              ++g_tests, what, (unsigned long long)k,
              (unsigned long long)kBudget);
  std::fflush(stdout);
}

}  // namespace

int main() {
  std::printf("ptpu_schedck_fixture_lostwake: r10 eventfd lost "
              "wakeup\n");
  CheckDiscovery(ChildDiscoverDfs, "dfs");
  // replay the DFS-found trace: identical failure, first schedule, 3x
  std::string prev;
  for (int i = 0; i < 3; ++i) {
    const std::string r = RunDeathTest(ChildReplay);
    if (r.find("strategy replay  schedule 0") == std::string::npos)
      fail("replay did not reproduce on the first schedule", r);
    if (r.find("DEADLOCK") == std::string::npos)
      fail("replay reproduced a different failure", r);
    if (i > 0 && r != prev)
      fail("replay reports differ across runs", r);
    prev = r;
  }
  ok("trace replays the identical deadlock, 3x, on schedule 0");
  CheckDiscovery(ChildDiscoverPct, "pct");
  std::remove(kTracePath);
  // negative control: the FIXED protocol is exhaustively clean
  {
    sck::Options o;
    o.strategy = sck::Options::Strategy::kDfs;
    o.max_schedules = 200000;
    o.depth = 10;
    const sck::Result r =
        sck::Explore("lostwake_fixed", FixedBody, o);
    if (!r.exhausted)
      fail("clean control did not exhaust the space", "");
    std::printf("ok %2d - fixed clear-then-swap protocol clean "
                "(%llu schedules, exhaustive)\n",
                ++g_tests, (unsigned long long)r.schedules);
  }
  std::remove("lostwake_buggy.schedck-trace");  // replay re-records
  std::printf("all lostwake fixture checks passed (%d tests)\n",
              g_tests);
  return 0;
}
