// Native-deployment predictor: load a paddle_tpu-exported ONNX artifact
// and execute it from C/C++ with NO Python in the serving process.
//
// Reference counterpart: the C inference API
// (paddle/fluid/inference/capi_exp/pd_inference_api.h:1) over
// AnalysisPredictor (inference/api/analysis_predictor.cc:381). The
// TPU-native deployment artifact is the ONNX wire file emitted by
// paddle_tpu.onnx.export (a jaxpr walk, onnx/converter.py); this TU is a
// dependency-free interpreter for exactly that op subset: a ~150-line
// protobuf wire parser + a dtype-tagged tensor interpreter. Heavy server
// deployments would hand the same artifact to an optimizing runtime; this
// keeps the "C caller, zero Python" contract testable and self-contained.
//
// Build: part of csrc/Makefile -> paddle_tpu/_native_predictor.so
// C ABI at the bottom (ptpu_predictor_*). Thread-compatible: one
// predictor per thread, no globals.
#include <algorithm>
#include <atomic>
#include <chrono>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <functional>
#include <fstream>
#include <map>
#include <memory>
#include <mutex>
#include <sstream>
#include <stdexcept>
#include <string>
#include <thread>
#include <vector>

namespace {

// ------------------------------------------------------------ protobuf wire
struct Reader {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;
  uint64_t varint() {
    uint64_t v = 0;
    int shift = 0;
    while (p < end) {
      uint8_t b = *p++;
      v |= uint64_t(b & 0x7F) << shift;
      if (!(b & 0x80)) return v;
      shift += 7;
      if (shift > 63) break;
    }
    ok = false;
    return 0;
  }
  // iterate fields; cb(field, wire, payload_reader_or_value)
  template <class F>
  void fields(F cb) {
    while (ok && p < end) {
      uint64_t key = varint();
      int field = int(key >> 3), wire = int(key & 7);
      if (wire == 0) {
        uint64_t v = varint();
        cb(field, wire, Reader{nullptr, nullptr}, v);
      } else if (wire == 2) {
        uint64_t len = varint();
        if (p + len > end) { ok = false; return; }
        cb(field, wire, Reader{p, p + len}, 0);
        p += len;
      } else if (wire == 5) {
        if (p + 4 > end) { ok = false; return; }
        cb(field, wire, Reader{p, p + 4}, 0);
        p += 4;
      } else if (wire == 1) {
        if (p + 8 > end) { ok = false; return; }
        cb(field, wire, Reader{p, p + 8}, 0);
        p += 8;
      } else {
        ok = false;
        return;
      }
    }
  }
  std::string str() const { return std::string((const char*)p, end - p); }
  std::vector<int64_t> packed_varints() const {
    Reader r{p, end};
    std::vector<int64_t> out;
    while (r.ok && r.p < r.end) {
      uint64_t v = r.varint();
      out.push_back(int64_t(v));  // two's complement for negatives
    }
    return out;
  }
};

// ----------------------------------------------------------------- tensors
// ONNX TensorProto dtype codes (subset)
enum { DT_F32 = 1, DT_U8 = 2, DT_I8 = 3, DT_I32 = 6, DT_I64 = 7,
       DT_BOOL = 9, DT_F64 = 11 };

struct Tensor {
  std::vector<int64_t> dims;
  int dtype = DT_F32;
  std::vector<float> f;    // DT_F32 / DT_F64 (converted)
  std::vector<int64_t> i;  // DT_I32 / DT_I64 / DT_BOOL / DT_U8
  int64_t numel() const {
    int64_t n = 1;
    for (auto d : dims) n *= d;
    return n;
  }
  bool is_float() const { return dtype == DT_F32 || dtype == DT_F64; }
  double at(int64_t k) const { return is_float() ? f[k] : double(i[k]); }
  void alloc() {
    if (is_float()) f.assign(size_t(numel()), 0.f);
    else i.assign(size_t(numel()), 0);
  }
  void set(int64_t k, double v) {
    if (is_float()) f[k] = float(v);
    else i[k] = int64_t(v);
  }
};

struct Attr {
  float fval = 0;
  int64_t ival = 0;
  std::string sval;
  std::vector<int64_t> ints;
  std::vector<float> floats;
  Tensor t;
  int type = 0;
};

struct Node {
  std::string op;
  std::vector<std::string> inputs, outputs;
  std::map<std::string, Attr> attrs;
};

struct Graph {
  std::vector<Node> nodes;
  std::map<std::string, Tensor> initializers;
  std::vector<std::string> input_names, output_names;
  std::map<std::string, std::vector<int64_t>> input_dims;
  std::map<std::string, int> input_dtypes;
};

Tensor parse_tensor(Reader r) {
  Tensor t;
  std::string raw;
  r.fields([&](int field, int wire, Reader sub, uint64_t v) {
    if (field == 1 && wire == 2) t.dims = sub.packed_varints();
    else if (field == 1 && wire == 0) t.dims.push_back(int64_t(v));
    else if (field == 2) t.dtype = int(v);
    else if (field == 9) raw = sub.str();
  });
  int64_t n = t.numel();
  if (t.dtype == DT_F32) {
    t.f.resize(size_t(n));
    if (raw.size() >= size_t(n) * 4) memcpy(t.f.data(), raw.data(), n * 4);
  } else if (t.dtype == DT_F64) {
    t.f.resize(size_t(n));
    const double* d = (const double*)raw.data();
    for (int64_t k = 0; k < n; ++k) t.f[size_t(k)] = float(d[k]);
    t.dtype = DT_F32;
  } else if (t.dtype == DT_I64) {
    t.i.resize(size_t(n));
    if (raw.size() >= size_t(n) * 8) memcpy(t.i.data(), raw.data(), n * 8);
  } else if (t.dtype == DT_I32) {
    t.i.resize(size_t(n));
    const int32_t* d = (const int32_t*)raw.data();
    for (int64_t k = 0; k < n; ++k) t.i[size_t(k)] = d[k];
  } else if (t.dtype == DT_BOOL || t.dtype == DT_U8) {
    t.i.resize(size_t(n));
    const uint8_t* d = (const uint8_t*)raw.data();
    for (int64_t k = 0; k < n; ++k) t.i[size_t(k)] = d[k];
  } else if (t.dtype == DT_I8) {
    t.i.resize(size_t(n));
    const int8_t* d = (const int8_t*)raw.data();
    for (int64_t k = 0; k < n; ++k) t.i[size_t(k)] = d[k];
  } else {
    throw std::runtime_error("initializer dtype " +
                             std::to_string(t.dtype) + " unsupported");
  }
  return t;
}

Attr parse_attr(Reader r, std::string* name) {
  Attr a;
  r.fields([&](int field, int wire, Reader sub, uint64_t v) {
    if (field == 1) *name = sub.str();
    else if (field == 2) memcpy(&a.fval, sub.p, 4);
    else if (field == 3) a.ival = int64_t(v);
    else if (field == 4) a.sval = sub.str();
    else if (field == 5) a.t = parse_tensor(sub);
    else if (field == 7) {  // packed floats
      const float* d = (const float*)sub.p;
      a.floats.assign(d, d + (sub.end - sub.p) / 4);
    } else if (field == 8) {
      if (wire == 2) a.ints = sub.packed_varints();
      else a.ints.push_back(int64_t(v));
    } else if (field == 20) a.type = int(v);
  });
  return a;
}

Node parse_node(Reader r) {
  Node n;
  r.fields([&](int field, int, Reader sub, uint64_t) {
    if (field == 1) n.inputs.push_back(sub.str());
    else if (field == 2) n.outputs.push_back(sub.str());
    else if (field == 4) n.op = sub.str();
    else if (field == 5) {
      std::string name;
      Attr a = parse_attr(sub, &name);
      n.attrs[name] = a;
    }
  });
  return n;
}

void parse_value_info(Reader r, std::string* name, std::vector<int64_t>* dims,
                      int* dtype) {
  r.fields([&](int field, int, Reader sub, uint64_t) {
    if (field == 1) *name = sub.str();
    else if (field == 2) {  // TypeProto
      sub.fields([&](int f2, int, Reader s2, uint64_t) {
        if (f2 != 1) return;  // tensor_type
        s2.fields([&](int f3, int, Reader s3, uint64_t v3) {
          if (f3 == 1) *dtype = int(v3);
          else if (f3 == 2) {  // shape
            s3.fields([&](int f4, int, Reader s4, uint64_t) {
              if (f4 != 1) return;  // dim
              s4.fields([&](int f5, int, Reader, uint64_t v5) {
                if (f5 == 1) dims->push_back(int64_t(v5));
              });
            });
          }
        });
      });
    }
  });
}

Graph parse_model(const std::string& bytes) {
  Graph g;
  Reader top{(const uint8_t*)bytes.data(),
             (const uint8_t*)bytes.data() + bytes.size()};
  top.fields([&](int field, int, Reader sub, uint64_t) {
    if (field != 7) return;  // ModelProto.graph
    sub.fields([&](int f2, int, Reader s2, uint64_t) {
      if (f2 == 1) g.nodes.push_back(parse_node(s2));
      else if (f2 == 5) {
        // initializer: need the name field (8) too
        std::string name;
        Reader nr = s2;
        nr.fields([&](int f3, int, Reader s3, uint64_t) {
          if (f3 == 8) name = s3.str();
        });
        g.initializers[name] = parse_tensor(s2);
      } else if (f2 == 11 || f2 == 12) {
        std::string name;
        std::vector<int64_t> dims;
        int dt = DT_F32;
        parse_value_info(s2, &name, &dims, &dt);
        if (f2 == 11) {
          g.input_names.push_back(name);
          g.input_dims[name] = dims;
          g.input_dtypes[name] = dt;
        } else {
          g.output_names.push_back(name);
        }
      }
    });
  });
  if (!top.ok) throw std::runtime_error("malformed model protobuf");
  return g;
}

// ------------------------------------------------------------ broadcasting
std::vector<int64_t> bcast_dims(const std::vector<int64_t>& a,
                                const std::vector<int64_t>& b) {
  size_t rank = std::max(a.size(), b.size());
  std::vector<int64_t> out(rank);
  for (size_t k = 0; k < rank; ++k) {
    int64_t da = k < rank - a.size() ? 1 : a[k - (rank - a.size())];
    int64_t db = k < rank - b.size() ? 1 : b[k - (rank - b.size())];
    if (da != db && da != 1 && db != 1)
      throw std::runtime_error("broadcast mismatch");
    out[k] = std::max(da, db);
  }
  return out;
}

std::vector<int64_t> strides_for(const std::vector<int64_t>& dims) {
  std::vector<int64_t> s(dims.size());
  int64_t acc = 1;
  for (int k = int(dims.size()) - 1; k >= 0; --k) {
    s[size_t(k)] = acc;
    acc *= dims[size_t(k)];
  }
  return s;
}

// index of `flat` (in out dims) within operand dims (right-aligned bcast)
int64_t bcast_index(int64_t flat, const std::vector<int64_t>& out_dims,
                    const std::vector<int64_t>& in_dims) {
  auto ostr = strides_for(out_dims);
  auto istr = strides_for(in_dims);
  int64_t idx = 0;
  size_t off = out_dims.size() - in_dims.size();
  for (size_t k = 0; k < out_dims.size(); ++k) {
    int64_t coord = (flat / ostr[k]) % out_dims[k];
    if (k >= off) {
      int64_t d = in_dims[k - off];
      idx += (d == 1 ? 0 : coord) * istr[k - off];
    }
  }
  return idx;
}

// ------------------------------------------------------------ fast path
// Deployment-class CPU execution (the reference's native engine is an
// optimized runtime — `inference/api/analysis_predictor.cc:381` runs an
// IR pass pipeline before an optimized executor). This block gives the
// C-ABI interpreter the three levers that matter on CPU: a blocked,
// multi-threaded SGEMM feeding MatMul AND Conv (via im2col), O(1)
// op-code dispatch resolved once per node instead of per-element string
// compares, and odometer index walks instead of per-element div/mod
// broadcasting.

static int num_threads() {
  static const int n = [] {
    const char* e = std::getenv("PTPU_PREDICTOR_THREADS");
    int v = e ? std::atoi(e) : 0;
    if (v <= 0) v = int(std::thread::hardware_concurrency());
    return std::max(1, std::min(v, 64));
  }();
  return n;
}

/* Persistent worker pool: spawning/joining std::threads per GEMM call
 * costs tens of microseconds x threads, paid once per node per
 * inference in a deep model. Workers park on a condition variable
 * between dispatches; the caller thread participates in the chunk
 * loop. Nested calls from inside a worker run serially (thread_local
 * guard) instead of deadlocking the pool. */
class WorkPool {
 public:
  static WorkPool& inst() {
    static WorkPool p(num_threads() - 1);
    return p;
  }

  void run(int64_t n, int64_t grain,
           const std::function<void(int64_t, int64_t)>& fn) {
    if (workers_.empty() || n <= grain || in_worker_) {
      fn(0, n);
      return;
    }
    const int64_t parts = int64_t(workers_.size() + 1) * 4;
    {
      std::lock_guard<std::mutex> l(mu_);
      fn_ = &fn;
      n_ = n;
      chunk_ = std::max(grain, (n + parts - 1) / parts);
      next_.store(0, std::memory_order_relaxed);
      done_ = 0;
      ++epoch_;
    }
    cv_go_.notify_all();
    drain(fn, n, chunk_);
    std::unique_lock<std::mutex> l(mu_);
    cv_done_.wait(l, [&] { return done_ == int(workers_.size()); });
    fn_ = nullptr;
  }

  ~WorkPool() {
    {
      std::lock_guard<std::mutex> l(mu_);
      stop_ = true;
    }
    cv_go_.notify_all();
    for (auto& t : workers_) t.join();
  }

 private:
  explicit WorkPool(int n_workers) {
    for (int t = 0; t < n_workers; ++t)
      workers_.emplace_back([this] { worker(); });
  }

  void drain(const std::function<void(int64_t, int64_t)>& fn, int64_t n,
             int64_t chunk) {
    for (;;) {
      const int64_t lo = next_.fetch_add(chunk, std::memory_order_relaxed);
      if (lo >= n) break;
      fn(lo, std::min(n, lo + chunk));
    }
  }

  void worker() {
    in_worker_ = true;
    int seen = 0;
    for (;;) {
      const std::function<void(int64_t, int64_t)>* fn;
      int64_t n, chunk;
      {
        std::unique_lock<std::mutex> l(mu_);
        cv_go_.wait(l, [&] { return stop_ || epoch_ != seen; });
        if (stop_) return;
        seen = epoch_;
        fn = fn_;
        n = n_;
        chunk = chunk_;
      }
      drain(*fn, n, chunk);
      {
        std::lock_guard<std::mutex> l(mu_);
        if (++done_ == int(workers_.size())) cv_done_.notify_one();
      }
    }
  }

  std::vector<std::thread> workers_;
  std::mutex mu_;
  std::condition_variable cv_go_, cv_done_;
  const std::function<void(int64_t, int64_t)>* fn_ = nullptr;
  int64_t n_ = 0, chunk_ = 1;
  std::atomic<int64_t> next_{0};
  int epoch_ = 0, done_ = 0;
  bool stop_ = false;
  static thread_local bool in_worker_;
};

thread_local bool WorkPool::in_worker_ = false;

template <class F>
static void parallel_for(int64_t n, int64_t grain, const F& fn) {
  WorkPool::inst().run(n, grain, fn);
}

/* C[M,N] = A[M,K] @ B[K,N], all row-major. Row-parallel; the j-inner
 * loop over a contiguous B row autovectorizes under -O2/-O3. fp32
 * accumulation (the scalar path accumulated in double; fp32 matches
 * what XLA's CPU GEMM does and is bit-compatible with the fp32
 * artifact contract). */
static void sgemm(const float* A, const float* B, float* C,
                  int64_t M, int64_t N, int64_t K) {
  parallel_for(M, std::max<int64_t>(int64_t(1), 16384 / std::max<int64_t>(N, 1)),
               [&](int64_t m0, int64_t m1) {
    constexpr int64_t KB = 128;  // K blocking keeps the B panel in L1/L2
    for (int64_t m = m0; m < m1; ++m)
      std::memset(C + m * N, 0, size_t(N) * sizeof(float));
    for (int64_t k0 = 0; k0 < K; k0 += KB) {
      const int64_t k1 = std::min(K, k0 + KB);
      for (int64_t m = m0; m < m1; ++m) {
        const float* a = A + m * K;
        float* c = C + m * N;
        for (int64_t k = k0; k < k1; ++k) {
          // no zero-skip: 0 * Inf/NaN must stay NaN (IEEE), matching
          // the scalar fallback and XLA on masked/one-hot operands
          const float av = a[k];
          const float* b = B + k * N;
          for (int64_t j = 0; j < N; ++j) c[j] += av * b[j];
        }
      }
    }
  });
}

/* Integer sibling of sgemm for the int8-executing artifacts. int32
 * lanes, not int64: int64 multiplies have no AVX2 form (the loop would
 * stay scalar — measured 16x slower than sgemm), while int8 operands
 * with int32 accumulation — the quantized-execution contract — are
 * exact for K up to 2^31 / 127^2 ~ 133K and vectorize fully. Callers
 * copy the widened int64 storage into int32 panels first. */
static void igemm(const int32_t* A, const int32_t* B, int32_t* C,
                  int64_t M, int64_t N, int64_t K) {
  parallel_for(M, std::max<int64_t>(int64_t(1),
                                    16384 / std::max<int64_t>(N, 1)),
               [&](int64_t m0, int64_t m1) {
    constexpr int64_t KB = 128;
    for (int64_t m = m0; m < m1; ++m)
      std::memset(C + m * N, 0, size_t(N) * sizeof(int32_t));
    for (int64_t k0 = 0; k0 < K; k0 += KB) {
      const int64_t k1 = std::min(K, k0 + KB);
      for (int64_t m = m0; m < m1; ++m) {
        const int32_t* a = A + m * K;
        int32_t* c = C + m * N;
        for (int64_t k = k0; k < k1; ++k) {
          const int32_t av = a[k];
          if (av == 0) continue;
          const int32_t* b = B + k * N;
          for (int64_t j = 0; j < N; ++j) c[j] += av * b[j];
        }
      }
    }
  });
}

/* Exact-int8 eligibility for the int32 GEMM paths (MatMul and Conv
 * share this): all operand values must fit int8, and the reduction
 * depth K must keep the worst-case accumulation 128*128*K strictly
 * below 2^31 (strict '<': K == 2^31/128^2 would reach exactly
 * INT32_MAX+1). */
static bool int8_exact(const std::vector<int64_t>& av,
                       const std::vector<int64_t>& bv, int64_t K) {
  if (K >= (int64_t(1) << 31) / (128 * 128)) return false;
  auto in8 = [](int64_t v) { return v >= -128 && v <= 127; };
  return std::all_of(av.begin(), av.end(), in8) &&
         std::all_of(bv.begin(), bv.end(), in8);
}

// op-code dispatch: resolved ONCE per node (see apply_binary/apply_unary
// below for the name->code mapping)
enum BinCode {
  B_ADD, B_SUB, B_MUL, B_DIV, B_MAX, B_MIN, B_POW, B_MOD, B_LT, B_LE,
  B_GT, B_GE, B_EQ, B_AND, B_OR, B_XOR, B_NONE
};
enum UnCode {
  U_NEG, U_ABS, U_EXP, U_LOG, U_SQRT, U_RECIP, U_SIGMOID, U_TANH, U_ERF,
  U_FLOOR, U_CEIL, U_ROUND, U_SIGN, U_RELU, U_NOT, U_SIN, U_COS, U_TAN,
  U_ASIN, U_ACOS, U_ATAN, U_SINH, U_COSH, U_ASINH, U_ACOSH, U_ATANH,
  U_NONE
};

static BinCode bin_code(const std::string& op) {
  static const std::map<std::string, BinCode> m = {
      {"Add", B_ADD}, {"Sub", B_SUB}, {"Mul", B_MUL}, {"Div", B_DIV},
      {"Max", B_MAX}, {"Min", B_MIN}, {"Pow", B_POW}, {"Mod", B_MOD},
      {"Less", B_LT}, {"LessOrEqual", B_LE}, {"Greater", B_GT},
      {"GreaterOrEqual", B_GE}, {"Equal", B_EQ}, {"And", B_AND},
      {"Or", B_OR}, {"Xor", B_XOR}};
  auto it = m.find(op);
  return it == m.end() ? B_NONE : it->second;
}

static UnCode un_code(const std::string& op) {
  static const std::map<std::string, UnCode> m = {
      {"Neg", U_NEG}, {"Abs", U_ABS}, {"Exp", U_EXP}, {"Log", U_LOG},
      {"Sqrt", U_SQRT}, {"Reciprocal", U_RECIP}, {"Sigmoid", U_SIGMOID},
      {"Tanh", U_TANH}, {"Erf", U_ERF}, {"Floor", U_FLOOR},
      {"Ceil", U_CEIL}, {"Round", U_ROUND}, {"Sign", U_SIGN},
      {"Relu", U_RELU}, {"Not", U_NOT}, {"Sin", U_SIN}, {"Cos", U_COS},
      {"Tan", U_TAN}, {"Asin", U_ASIN}, {"Acos", U_ACOS},
      {"Atan", U_ATAN}, {"Sinh", U_SINH}, {"Cosh", U_COSH},
      {"Asinh", U_ASINH}, {"Acosh", U_ACOSH}, {"Atanh", U_ATANH}};
  auto it = m.find(op);
  return it == m.end() ? U_NONE : it->second;
}

static double apply_bin_code(BinCode c, double a, double b) {
  switch (c) {
    case B_ADD: return a + b;
    case B_SUB: return a - b;
    case B_MUL: return a * b;
    case B_DIV: return a / b;
    case B_MAX: return std::max(a, b);
    case B_MIN: return std::min(a, b);
    case B_POW: return std::pow(a, b);
    case B_MOD: return std::fmod(a, b);
    case B_LT: return a < b;
    case B_LE: return a <= b;
    case B_GT: return a > b;
    case B_GE: return a >= b;
    case B_EQ: return a == b;
    case B_AND: return (a != 0) && (b != 0);
    case B_OR: return (a != 0) || (b != 0);
    case B_XOR: return (a != 0) != (b != 0);
    default: throw std::runtime_error("bad binary code");
  }
}

static double apply_un_code(UnCode c, double a) {
  switch (c) {
    case U_NEG: return -a;
    case U_ABS: return std::fabs(a);
    case U_EXP: return std::exp(a);
    case U_LOG: return std::log(a);
    case U_SQRT: return std::sqrt(a);
    case U_RECIP: return 1.0 / a;
    case U_SIGMOID: return 1.0 / (1.0 + std::exp(-a));
    case U_TANH: return std::tanh(a);
    case U_ERF: return std::erf(a);
    case U_FLOOR: return std::floor(a);
    case U_CEIL: return std::ceil(a);
    case U_ROUND: return std::nearbyint(a);
    case U_SIGN: return a > 0 ? 1 : (a < 0 ? -1 : 0);
    case U_RELU: return a > 0 ? a : 0;
    case U_NOT: return a == 0;
    case U_SIN: return std::sin(a);
    case U_COS: return std::cos(a);
    case U_TAN: return std::tan(a);
    case U_ASIN: return std::asin(a);
    case U_ACOS: return std::acos(a);
    case U_ATAN: return std::atan(a);
    case U_SINH: return std::sinh(a);
    case U_COSH: return std::cosh(a);
    case U_ASINH: return std::asinh(a);
    case U_ACOSH: return std::acosh(a);
    case U_ATANH: return std::atanh(a);
    default: throw std::runtime_error("bad unary code");
  }
}

/* Walk every element of the broadcast output, handing the callback the
 * flat output index plus both operand indices — incremental odometer
 * carries instead of the old per-element div/mod chains. */
template <class F>
static void bcast_walk(const std::vector<int64_t>& odims,
                       const std::vector<int64_t>& adims,
                       const std::vector<int64_t>& bdims, const F& f) {
  const size_t r = odims.size();
  int64_t total = 1;
  for (auto d : odims) total *= d;
  if (r == 0) {
    if (total) f(int64_t(0), int64_t(0), int64_t(0));
    return;
  }
  auto as = strides_for(adims), bs = strides_for(bdims);
  std::vector<int64_t> ast(r, 0), bst(r, 0), ctr(r, 0);
  const size_t ao = r - adims.size(), bo = r - bdims.size();
  for (size_t d = 0; d < r; ++d) {
    if (d >= ao && adims[d - ao] != 1) ast[d] = as[d - ao];
    if (d >= bo && bdims[d - bo] != 1) bst[d] = bs[d - bo];
  }
  int64_t ai = 0, bi = 0;
  for (int64_t k = 0; k < total; ++k) {
    f(k, ai, bi);
    for (size_t d = r; d-- > 0;) {
      ++ctr[d];
      ai += ast[d];
      bi += bst[d];
      if (ctr[d] < odims[d]) break;
      ai -= ast[d] * odims[d];
      bi -= bst[d] * odims[d];
      ctr[d] = 0;
    }
  }
}

// ----------------------------------------------------------------- executor
struct Predictor {
  Graph g;
  std::map<std::string, Tensor> env;
  std::vector<Tensor> outputs;
  std::vector<std::string> last_err_names;

  const Tensor& in(const Node& n, size_t k) {
    auto it = env.find(n.inputs[k]);
    if (it == env.end())
      throw std::runtime_error("missing input tensor '" + n.inputs[k] +
                               "' for op " + n.op);
    return it->second;
  }

  static int64_t attr_i(const Node& n, const char* name, int64_t dflt) {
    auto it = n.attrs.find(name);
    return it == n.attrs.end() ? dflt : it->second.ival;
  }
  static std::vector<int64_t> attr_ints(const Node& n, const char* name) {
    auto it = n.attrs.find(name);
    return it == n.attrs.end() ? std::vector<int64_t>{} : it->second.ints;
  }

  void run_node(const Node& n);
  /* Constant folding — the load-time optimization pass (reference:
   * AnalysisPredictor::OptimizeInferenceProgram's pass pipeline,
   * `inference/api/analysis_predictor.cc:621`). Any node whose inputs
   * are all initializers (or folded outputs) runs ONCE here and its
   * outputs become initializers. The big win is int8 artifacts: the
   * whole weight-quantization subgraph (Abs/ReduceMax/Div/Round/Clip/
   * Cast over every weight matrix) folds away, leaving only activation
   * quantization + the integer GEMM at serve time. */
  void fold_constants() {
    std::vector<Node> kept;
    for (const auto& n : g.nodes) {
      bool all_const = true;
      for (const auto& i : n.inputs)
        if (!g.initializers.count(i)) { all_const = false; break; }
      if (!all_const) {
        kept.push_back(n);
        continue;
      }
      try {
        run_node(n);
      } catch (const std::exception&) {
        kept.push_back(n);  // unsupported here -> fails at run() as before
        continue;
      }
      for (const auto& o : n.outputs) g.initializers[o] = env[o];
    }
    g.nodes.swap(kept);
    // a folded-away intermediate read by no surviving node can be freed
    std::map<std::string, int> live;
    for (const auto& n : g.nodes)
      for (const auto& i : n.inputs) ++live[i];
    for (const auto& name : g.output_names) ++live[name];
    for (auto it = g.initializers.begin(); it != g.initializers.end();) {
      if (!live.count(it->first)) {
        env.erase(it->first);
        it = g.initializers.erase(it);
      } else {
        ++it;
      }
    }
  }

  void run() {
    outputs.clear();
    static const bool profile =
        std::getenv("PTPU_PREDICTOR_PROFILE") != nullptr;
    if (profile) {
      // per-op-type cumulative wall time to stderr — the doctor's view
      // for "which op dominates this artifact"
      std::map<std::string, double> acc;
      for (const auto& n : g.nodes) {
        auto t0 = std::chrono::steady_clock::now();
        run_node(n);
        acc[n.op] += std::chrono::duration<double>(
            std::chrono::steady_clock::now() - t0).count();
      }
      for (const auto& kv : acc)
        std::fprintf(stderr, "ptpu_profile %-20s %.3f ms\n",
                     kv.first.c_str(), kv.second * 1e3);
    } else {
      for (const auto& n : g.nodes) run_node(n);
    }
    for (const auto& name : g.output_names) {
      auto it = env.find(name);
      if (it == env.end())
        throw std::runtime_error("output '" + name + "' never produced");
      outputs.push_back(it->second);
    }
  }
};


static const char* kBinaryOps[] = {
    "Add", "Sub", "Mul", "Div", "Max", "Min", "Pow", "Mod", "Less",
    "LessOrEqual", "Greater", "GreaterOrEqual", "Equal", "And", "Or",
    "Xor"};
static const char* kUnaryOps[] = {
    "Neg", "Abs", "Exp", "Log", "Sqrt", "Reciprocal", "Sigmoid", "Tanh",
    "Erf", "Floor", "Ceil", "Round", "Sign", "Relu", "Not", "Sin", "Cos",
    "Tan", "Asin", "Acos", "Atan", "Sinh", "Cosh", "Asinh", "Acosh",
    "Atanh"};

bool contains(const char* const* arr, size_t n, const std::string& s) {
  for (size_t k = 0; k < n; ++k)
    if (s == arr[k]) return true;
  return false;
}

void Predictor::run_node(const Node& n) {
  const std::string& op = n.op;
  auto out = [&](Tensor t) { env[n.outputs[0]] = std::move(t); };

  if (op == "Identity") {
    env[n.outputs[0]] = in(n, 0);
  } else if (contains(kBinaryOps, sizeof(kBinaryOps) / sizeof(char*), op)) {
    const Tensor &a = in(n, 0), &b = in(n, 1);
    Tensor o;
    o.dims = bcast_dims(a.dims, b.dims);
    bool cmp = (op == "Less" || op == "LessOrEqual" || op == "Greater" ||
                op == "GreaterOrEqual" || op == "Equal" || op == "And" ||
                op == "Or" || op == "Xor");
    o.dtype = cmp ? DT_BOOL
                  : ((a.is_float() || b.is_float()) ? DT_F32 : a.dtype);
    o.alloc();
    const BinCode code = bin_code(op);  // resolved once, not per element
    if (a.is_float() && b.is_float() && o.dtype == DT_F32) {
      const float *af = a.f.data(), *bf = b.f.data();
      float* of = o.f.data();
      switch (code) {  // the arithmetic hot set gets branch-free loops
        case B_ADD:
          bcast_walk(o.dims, a.dims, b.dims, [&](int64_t k, int64_t ai,
              int64_t bi) { of[k] = af[ai] + bf[bi]; });
          break;
        case B_SUB:
          bcast_walk(o.dims, a.dims, b.dims, [&](int64_t k, int64_t ai,
              int64_t bi) { of[k] = af[ai] - bf[bi]; });
          break;
        case B_MUL:
          bcast_walk(o.dims, a.dims, b.dims, [&](int64_t k, int64_t ai,
              int64_t bi) { of[k] = af[ai] * bf[bi]; });
          break;
        case B_DIV:
          bcast_walk(o.dims, a.dims, b.dims, [&](int64_t k, int64_t ai,
              int64_t bi) { of[k] = af[ai] / bf[bi]; });
          break;
        case B_MAX:
          bcast_walk(o.dims, a.dims, b.dims, [&](int64_t k, int64_t ai,
              int64_t bi) { of[k] = std::max(af[ai], bf[bi]); });
          break;
        case B_MIN:
          bcast_walk(o.dims, a.dims, b.dims, [&](int64_t k, int64_t ai,
              int64_t bi) { of[k] = std::min(af[ai], bf[bi]); });
          break;
        case B_POW:
          // GELU/LN graphs are full of pow(x, 2|3|0.5) with a scalar
          // exponent — std::pow per element is ~20x a multiply
          if (b.numel() == 1 && bf[0] == 2.0f) {
            for (int64_t k = 0; k < o.numel(); ++k)
              of[k] = af[k] * af[k];
          } else if (b.numel() == 1 && bf[0] == 3.0f) {
            for (int64_t k = 0; k < o.numel(); ++k)
              of[k] = af[k] * af[k] * af[k];
          } else {
            // no sqrt shortcut for exponent 0.5: IEEE pow(-inf, .5)
            // is +inf and pow(-0., .5) is +0., sqrt disagrees on both
            bcast_walk(o.dims, a.dims, b.dims, [&](int64_t k, int64_t ai,
                int64_t bi) { of[k] = std::pow(af[ai], bf[bi]); });
          }
          break;
        default:
          bcast_walk(o.dims, a.dims, b.dims, [&](int64_t k, int64_t ai,
              int64_t bi) {
            o.set(k, apply_bin_code(code, af[ai], bf[bi]));
          });
      }
    } else {
      bcast_walk(o.dims, a.dims, b.dims,
                 [&](int64_t k, int64_t ai, int64_t bi) {
        o.set(k, apply_bin_code(code, a.at(ai), b.at(bi)));
      });
    }
    out(std::move(o));
  } else if (contains(kUnaryOps, sizeof(kUnaryOps) / sizeof(char*), op)) {
    const Tensor& a = in(n, 0);
    Tensor o;
    o.dims = a.dims;
    o.dtype = (op == "Not") ? DT_BOOL : a.dtype;
    o.alloc();
    const UnCode code = un_code(op);
    const int64_t nel = o.numel();
    if (a.is_float() && o.is_float()) {
      const float* af = a.f.data();
      float* of = o.f.data();
      switch (code) {
        case U_RELU:
          for (int64_t k = 0; k < nel; ++k)
            of[k] = af[k] > 0.f ? af[k] : 0.f;
          break;
        case U_NEG:
          for (int64_t k = 0; k < nel; ++k) of[k] = -af[k];
          break;
        case U_ABS:
          for (int64_t k = 0; k < nel; ++k) of[k] = std::fabs(af[k]);
          break;
        case U_SQRT:
          for (int64_t k = 0; k < nel; ++k) of[k] = std::sqrt(af[k]);
          break;
        default:
          for (int64_t k = 0; k < nel; ++k)
            of[k] = float(apply_un_code(code, af[k]));
      }
    } else {
      for (int64_t k = 0; k < nel; ++k)
        o.set(k, apply_un_code(code, a.at(k)));
    }
    out(std::move(o));
  } else if (op == "Clip") {
    const Tensor& a = in(n, 0);
    double lo = in(n, 1).at(0), hi = in(n, 2).at(0);
    Tensor o = a;
    for (int64_t k = 0; k < o.numel(); ++k)
      o.set(k, std::min(hi, std::max(lo, a.at(k))));
    out(std::move(o));
  } else if (op == "Where") {
    const Tensor &c = in(n, 0), &x = in(n, 1), &y = in(n, 2);
    Tensor o;
    o.dims = bcast_dims(bcast_dims(c.dims, x.dims), y.dims);
    o.dtype = x.dtype;
    o.alloc();
    for (int64_t k = 0; k < o.numel(); ++k) {
      bool cond = c.at(bcast_index(k, o.dims, c.dims)) != 0;
      o.set(k, cond ? x.at(bcast_index(k, o.dims, x.dims))
                    : y.at(bcast_index(k, o.dims, y.dims)));
    }
    out(std::move(o));
  } else if (op == "Cast") {
    const Tensor& a = in(n, 0);
    Tensor o;
    o.dims = a.dims;
    o.dtype = int(attr_i(n, "to", DT_F32));
    if (o.dtype == DT_F64) o.dtype = DT_F32;
    o.alloc();
    for (int64_t k = 0; k < o.numel(); ++k) {
      double v = a.at(k);
      if (o.dtype == DT_BOOL) v = (v != 0);
      else if (o.dtype == DT_I8)   // wrap like a C int8_t conversion
        v = double(int8_t(int64_t(v)));
      o.set(k, v);
    }
    out(std::move(o));
  } else if (op == "Reshape") {
    const Tensor& a = in(n, 0);
    const Tensor& shp = in(n, 1);
    Tensor o = a;
    o.dims.assign(shp.i.begin(), shp.i.end());
    out(std::move(o));
  } else if (op == "Transpose") {
    const Tensor& a = in(n, 0);
    auto perm = attr_ints(n, "perm");
    if (perm.empty())  // ONNX default: reverse the axes
      for (size_t d = a.dims.size(); d-- > 0;)
        perm.push_back(int64_t(d));
    Tensor o;
    o.dtype = a.dtype;
    o.dims.resize(a.dims.size());
    for (size_t k = 0; k < perm.size(); ++k)
      o.dims[k] = a.dims[size_t(perm[k])];
    o.alloc();
    // odometer walk: src index updated incrementally per output
    // element (every attention matmul lowers through Transpose — the
    // old per-element div/mod chain dominated transformer serving)
    auto istr = strides_for(a.dims);
    const size_t r = o.dims.size();
    std::vector<int64_t> sstr(r), ctr(r, 0);
    for (size_t d = 0; d < r; ++d) sstr[d] = istr[size_t(perm[d])];
    const int64_t nel = o.numel();
    int64_t src = 0;
    const bool flt = a.is_float();
    for (int64_t k = 0; k < nel; ++k) {
      if (flt) o.f[size_t(k)] = a.f[size_t(src)];
      else o.i[size_t(k)] = a.i[size_t(src)];
      for (size_t d = r; d-- > 0;) {
        ++ctr[d];
        src += sstr[d];
        if (ctr[d] < o.dims[d]) break;
        src -= sstr[d] * o.dims[d];
        ctr[d] = 0;
      }
    }
    out(std::move(o));
  } else if (op == "Concat") {
    int64_t rank = int64_t(in(n, 0).dims.size());
    int64_t axis = attr_i(n, "axis", 0);
    if (axis < 0) axis += rank;
    Tensor o;
    o.dtype = in(n, 0).dtype;
    o.dims = in(n, 0).dims;
    int64_t total = 0;
    for (size_t k = 0; k < n.inputs.size(); ++k)
      total += in(n, k).dims[size_t(axis)];
    o.dims[size_t(axis)] = total;
    o.alloc();
    auto ostr = strides_for(o.dims);
    int64_t offset = 0;
    for (size_t t = 0; t < n.inputs.size(); ++t) {
      const Tensor& a = in(n, t);
      auto istr = strides_for(a.dims);
      for (int64_t k = 0; k < a.numel(); ++k) {
        int64_t dst = 0;
        for (size_t d = 0; d < a.dims.size(); ++d) {
          int64_t coord = (k / istr[d]) % a.dims[d];
          if (int64_t(d) == axis) coord += offset;
          dst += coord * ostr[d];
        }
        o.set(dst, a.at(k));
      }
      offset += a.dims[size_t(axis)];
    }
    out(std::move(o));
  } else if (op == "Expand") {
    const Tensor& a = in(n, 0);
    const Tensor& shp = in(n, 1);
    std::vector<int64_t> want(shp.i.begin(), shp.i.end());
    Tensor o;
    o.dims = bcast_dims(a.dims, want);
    o.dtype = a.dtype;
    o.alloc();
    for (int64_t k = 0; k < o.numel(); ++k)
      o.set(k, a.at(bcast_index(k, o.dims, a.dims)));
    out(std::move(o));
  } else if (op == "Slice") {
    const Tensor& a = in(n, 0);
    const Tensor &st = in(n, 1), &en = in(n, 2);
    std::vector<int64_t> axes, steps;
    if (n.inputs.size() > 3)
      axes.assign(in(n, 3).i.begin(), in(n, 3).i.end());
    else
      for (size_t k = 0; k < st.i.size(); ++k) axes.push_back(int64_t(k));
    if (n.inputs.size() > 4)
      steps.assign(in(n, 4).i.begin(), in(n, 4).i.end());
    else
      steps.assign(axes.size(), 1);
    std::vector<int64_t> begin(a.dims.size(), 0), stride(a.dims.size(), 1),
        count = a.dims;
    for (size_t k = 0; k < axes.size(); ++k) {
      int64_t ax = axes[k] < 0 ? axes[k] + int64_t(a.dims.size()) : axes[k];
      int64_t dim = a.dims[size_t(ax)];
      int64_t s = st.i[k], e = en.i[k], sp = steps[k];
      if (s < 0) s += dim;
      if (e < -dim) e = sp < 0 ? -1 : 0;  // INT64_MIN+1 marker for reverse
      else if (e < 0) e += dim;
      if (sp > 0) {
        s = std::min(std::max(s, int64_t(0)), dim);
        e = std::min(std::max(e, int64_t(0)), dim);
        count[size_t(ax)] = std::max(int64_t(0), (e - s + sp - 1) / sp);
      } else {
        s = std::min(std::max(s, int64_t(0)), dim - 1);
        e = std::max(e, int64_t(-1));
        count[size_t(ax)] = std::max(int64_t(0), (s - e - sp - 1) / (-sp));
      }
      begin[size_t(ax)] = s;
      stride[size_t(ax)] = sp;
    }
    Tensor o;
    o.dims = count;
    o.dtype = a.dtype;
    o.alloc();
    auto istr = strides_for(a.dims);
    const size_t r = o.dims.size();
    /* odometer + contiguous-tail memcpy: find the longest suffix of
     * unit-step, full-width axes — those positions copy as one run. */
    size_t tail = r;
    int64_t run = 1;
    while (tail > 0 && stride[tail - 1] == 1 && begin[tail - 1] == 0 &&
           count[tail - 1] == a.dims[tail - 1]) {
      --tail;
      run *= count[tail];
    }
    // src base index for the block at the current odometer position
    std::vector<int64_t> ctr(r, 0);
    int64_t base = 0;
    for (size_t d = 0; d < tail; ++d) base += begin[d] * istr[d];
    const int64_t blocks = o.numel() / std::max<int64_t>(run, 1);
    const bool flt = a.is_float();
    for (int64_t b = 0; b < blocks; ++b) {
      if (flt)
        std::memcpy(o.f.data() + b * run, a.f.data() + base,
                    size_t(run) * sizeof(float));
      else
        std::memcpy(o.i.data() + b * run, a.i.data() + base,
                    size_t(run) * sizeof(int64_t));
      for (size_t d = tail; d-- > 0;) {
        ++ctr[d];
        base += stride[d] * istr[d];
        if (ctr[d] < count[d]) break;
        base -= stride[d] * istr[d] * count[d];
        ctr[d] = 0;
      }
    }
    out(std::move(o));
  } else if (op == "Gather") {
    const Tensor &a = in(n, 0), &idx = in(n, 1);
    int64_t axis = attr_i(n, "axis", 0);
    if (axis < 0) axis += int64_t(a.dims.size());
    Tensor o;
    o.dtype = a.dtype;
    for (int64_t d = 0; d < axis; ++d) o.dims.push_back(a.dims[size_t(d)]);
    for (auto d : idx.dims) o.dims.push_back(d);
    for (size_t d = size_t(axis) + 1; d < a.dims.size(); ++d)
      o.dims.push_back(a.dims[d]);
    o.alloc();
    int64_t ax_dim = a.dims[size_t(axis)];
    /* row-copy formulation: output = [outer, idx..., inner] where
     * inner = contiguous tail of `a` after `axis` — copy `inner`
     * elements per (outer, index) pair instead of re-deriving every
     * coordinate per element. */
    int64_t inner = 1;
    for (size_t d = size_t(axis) + 1; d < a.dims.size(); ++d)
      inner *= a.dims[d];
    int64_t outer = 1;
    for (int64_t d = 0; d < axis; ++d) outer *= a.dims[size_t(d)];
    const int64_t nidx = idx.numel();
    for (int64_t ou = 0; ou < outer; ++ou)
      for (int64_t j = 0; j < nidx; ++j) {
        int64_t iv = idx.i.empty() ? int64_t(idx.at(j)) : idx.i[size_t(j)];
        if (iv < 0) iv += ax_dim;
        const int64_t src = (ou * ax_dim + iv) * inner;
        const int64_t dst = (ou * nidx + j) * inner;
        if (a.is_float())
          std::memcpy(o.f.data() + dst, a.f.data() + src,
                      size_t(inner) * sizeof(float));
        else
          std::memcpy(o.i.data() + dst, a.i.data() + src,
                      size_t(inner) * sizeof(int64_t));
      }
    out(std::move(o));
  } else if (op == "MatMul") {
    const Tensor &a = in(n, 0), &b = in(n, 1);
    const size_t ra = a.dims.size(), rb = b.dims.size();
    const bool batched_b = rb > 2;
    int64_t k_d = a.dims.back();
    int64_t m = ra >= 2 ? a.dims[ra - 2] : 1;
    int64_t nn, batch;
    Tensor o;
    o.dtype = DT_F32;
    if (batched_b) {
      /* [B..., M, K] x [B..., K, N] — the ONNX exporter lowers every
       * jax dot_general (attention included) to this via
       * transpose/reshape, so transformer artifacts serve natively. */
      if (ra != rb) throw std::runtime_error("MatMul: batched ranks differ");
      batch = 1;
      for (size_t d = 0; d + 2 < ra; ++d) {
        if (a.dims[d] != b.dims[d])
          throw std::runtime_error("MatMul: batch dims differ");
        batch *= a.dims[d];
      }
      if (b.dims[rb - 2] != k_d)
        throw std::runtime_error("MatMul: inner dims differ");
      nn = b.dims[rb - 1];
      o.dims.assign(a.dims.begin(), a.dims.end() - 1);
      o.dims.push_back(nn);
    } else {
      nn = rb == 2 ? b.dims[1] : 1;
      batch = a.numel() / (k_d * m);
      o.dims.assign(a.dims.begin(), a.dims.end() - 1);
      if (rb == 2) o.dims.push_back(nn);
    }
    o.alloc();
    if (a.is_float() && b.is_float() && rb >= 2) {
      // blocked threaded SGEMM; for non-batched B every batch reuses
      // the same [K,N] panel, for batched B each batch has its own
      for (int64_t bb = 0; bb < batch; ++bb)
        sgemm(a.f.data() + bb * m * k_d,
              b.f.data() + (batched_b ? bb * k_d * nn : 0),
              o.f.data() + bb * m * nn, m, nn, k_d);
    } else if (!a.is_float() && !b.is_float() && rb >= 2 &&
               // int8-range guard: this path is EXACT only for int8
               // operands; int64 index/counter arithmetic must keep
               // the exact double-accumulating scalar path
               int8_exact(a.i, b.i, k_d)) {
      // int8-executing artifacts: int32 GEMM (exact for the int8 value
      // range at this K; anything else falls through to the scalar path)
      std::vector<int32_t> a32(size_t(m * k_d)), acc(size_t(m * nn));
      std::vector<int32_t> b32(size_t(k_d * nn));
      for (int64_t bb = 0; bb < batch; ++bb) {
        const int64_t* ap = a.i.data() + bb * m * k_d;
        for (int64_t k = 0; k < m * k_d; ++k) a32[size_t(k)] = int32_t(ap[k]);
        const int64_t* bp = b.i.data() + (batched_b ? bb * k_d * nn : 0);
        if (bb == 0 || batched_b)
          for (int64_t k = 0; k < k_d * nn; ++k)
            b32[size_t(k)] = int32_t(bp[k]);
        igemm(a32.data(), b32.data(), acc.data(), m, nn, k_d);
        float* of = o.f.data() + bb * m * nn;
        for (int64_t k = 0; k < m * nn; ++k) of[k] = float(acc[size_t(k)]);
      }
    } else {
      for (int64_t bb = 0; bb < batch; ++bb)
        for (int64_t mm = 0; mm < m; ++mm)
          for (int64_t jj = 0; jj < nn; ++jj) {
            double acc = 0;
            for (int64_t kk = 0; kk < k_d; ++kk)
              acc += a.at((bb * m + mm) * k_d + kk) *
                     b.at(batched_b ? (bb * k_d + kk) * nn + jj
                                    : (rb == 2 ? kk * nn + jj : kk));
            o.set((bb * m + mm) * nn + jj, acc);
          }
    }
    out(std::move(o));
  } else if (op == "Conv") {
    const Tensor &x = in(n, 0), &w = in(n, 1);
    if (x.dims.size() != 4) throw std::runtime_error("Conv: only 2-D");
    auto strides = attr_ints(n, "strides");
    auto pads = attr_ints(n, "pads");
    auto dil = attr_ints(n, "dilations");
    int64_t group = attr_i(n, "group", 1);
    if (strides.empty()) strides = {1, 1};
    if (pads.empty()) pads = {0, 0, 0, 0};
    if (dil.empty()) dil = {1, 1};
    int64_t N = x.dims[0], C = x.dims[1], H = x.dims[2], W = x.dims[3];
    int64_t OC = w.dims[0], ICG = w.dims[1], KH = w.dims[2], KW = w.dims[3];
    int64_t OH = (H + pads[0] + pads[2] - dil[0] * (KH - 1) - 1) /
                     strides[0] + 1;
    int64_t OW = (W + pads[1] + pads[3] - dil[1] * (KW - 1) - 1) /
                     strides[1] + 1;
    int64_t ocg = OC / group;
    Tensor o;
    o.dtype = DT_F32;
    o.dims = {N, OC, OH, OW};
    o.alloc();
    if (x.is_float() && w.is_float()) {
      /* im2col + SGEMM: per (image, group) build the patch matrix
       * col[ICG*KH*KW, OH*OW] once, then the conv is one GEMM of the
       * group's [ocg, ICG*KH*KW] filters against it — the MXU-style
       * formulation, here feeding the threaded CPU GEMM. 1x1/s1/p0
       * convs skip the copy: the input slice IS the col matrix. */
      const int64_t P = OH * OW, CK = ICG * KH * KW;
      const bool unit = (KH == 1 && KW == 1 && strides[0] == 1 &&
                         strides[1] == 1 && pads[0] == 0 && pads[1] == 0 &&
                         pads[2] == 0 && pads[3] == 0);
      std::vector<float> col;
      if (!unit) col.resize(size_t(CK * P));
      for (int64_t nn = 0; nn < N; ++nn)
        for (int64_t g = 0; g < group; ++g) {
          const float* xg = x.f.data() + (nn * C + g * ICG) * H * W;
          const float* src = xg;
          if (!unit) {
            float* cp = col.data();
            parallel_for(CK, 64, [&](int64_t r0, int64_t r1) {
              for (int64_t r = r0; r < r1; ++r) {
                const int64_t ic = r / (KH * KW);
                const int64_t kh = (r / KW) % KH, kw = r % KW;
                float* dst = cp + r * P;
                const float* plane = xg + ic * H * W;
                for (int64_t oh = 0; oh < OH; ++oh) {
                  const int64_t ih = oh * strides[0] - pads[0] + kh * dil[0];
                  if (ih < 0 || ih >= H) {
                    std::memset(dst + oh * OW, 0, size_t(OW) * sizeof(float));
                    continue;
                  }
                  const float* row = plane + ih * W;
                  for (int64_t ow = 0; ow < OW; ++ow) {
                    const int64_t iw = ow * strides[1] - pads[1] + kw * dil[1];
                    dst[oh * OW + ow] =
                        (iw < 0 || iw >= W) ? 0.f : row[iw];
                  }
                }
              }
            });
            src = cp;
          }
          sgemm(w.f.data() + g * ocg * CK, src,
                o.f.data() + (nn * OC + g * ocg) * P, ocg, P, CK);
        }
    } else if (!x.is_float() && !w.is_float() &&
               int8_exact(x.i, w.i, ICG * KH * KW)) {
      /* int8-executing conv (QAT convert_to_int8 artifacts): same
       * im2col formulation feeding the int32 GEMM — exact for int8
       * operands with int32 accumulation. Group outer so each group's
       * weight panel widens to int32 ONCE, not once per image. */
      const int64_t P = OH * OW, CK = ICG * KH * KW;
      std::vector<int32_t> col(size_t(CK * P)), w32(size_t(ocg * CK));
      std::vector<int32_t> acc(size_t(ocg * P));
      for (int64_t g = 0; g < group; ++g) {
        const int64_t* wg = w.i.data() + g * ocg * CK;
        for (int64_t k = 0; k < ocg * CK; ++k)
          w32[size_t(k)] = int32_t(wg[k]);
        for (int64_t nn = 0; nn < N; ++nn) {
          const int64_t* xg = x.i.data() + (nn * C + g * ICG) * H * W;
          parallel_for(CK, 64, [&](int64_t r0, int64_t r1) {
            for (int64_t rr = r0; rr < r1; ++rr) {
              const int64_t ic = rr / (KH * KW);
              const int64_t kh = (rr / KW) % KH, kw = rr % KW;
              int32_t* dst = col.data() + rr * P;
              const int64_t* plane = xg + ic * H * W;
              for (int64_t oh = 0; oh < OH; ++oh) {
                const int64_t ih = oh * strides[0] - pads[0] + kh * dil[0];
                if (ih < 0 || ih >= H) {  // hoisted like the float path
                  std::memset(dst + oh * OW, 0,
                              size_t(OW) * sizeof(int32_t));
                  continue;
                }
                const int64_t* row = plane + ih * W;
                for (int64_t ow = 0; ow < OW; ++ow) {
                  const int64_t iw = ow * strides[1] - pads[1] + kw * dil[1];
                  dst[oh * OW + ow] =
                      (iw < 0 || iw >= W) ? 0 : int32_t(row[iw]);
                }
              }
            }
          });
          igemm(w32.data(), col.data(), acc.data(), ocg, P, CK);
          float* of = o.f.data() + (nn * OC + g * ocg) * P;
          for (int64_t k = 0; k < ocg * P; ++k) of[k] = float(acc[size_t(k)]);
        }
      }
    } else {
      for (int64_t nn = 0; nn < N; ++nn)
        for (int64_t oc = 0; oc < OC; ++oc) {
          int64_t g0 = (oc / ocg) * ICG;  // first input channel of group
          for (int64_t oh = 0; oh < OH; ++oh)
            for (int64_t ow = 0; ow < OW; ++ow) {
              double acc = 0;
              for (int64_t ic = 0; ic < ICG; ++ic)
                for (int64_t kh = 0; kh < KH; ++kh) {
                  int64_t ih = oh * strides[0] - pads[0] + kh * dil[0];
                  if (ih < 0 || ih >= H) continue;
                  for (int64_t kw = 0; kw < KW; ++kw) {
                    int64_t iw = ow * strides[1] - pads[1] + kw * dil[1];
                    if (iw < 0 || iw >= W) continue;
                    acc += x.at(((nn * C + g0 + ic) * H + ih) * W + iw) *
                           w.at(((oc * ICG + ic) * KH + kh) * KW + kw);
                  }
                }
              o.f[size_t(((nn * OC + oc) * OH + oh) * OW + ow)] = float(acc);
            }
        }
    }
    out(std::move(o));
  } else if (op == "MaxPool" || op == "AveragePool") {
    const Tensor& x = in(n, 0);
    auto ks = attr_ints(n, "kernel_shape");
    auto strides = attr_ints(n, "strides");
    auto pads = attr_ints(n, "pads");
    if (strides.empty()) strides.assign(ks.size(), 1);
    if (pads.empty()) pads.assign(ks.size() * 2, 0);
    if (x.dims.size() != 4 || ks.size() != 2)
      throw std::runtime_error(op + ": only 2-D");
    bool include_pad = attr_i(n, "count_include_pad", 0) != 0;
    int64_t N = x.dims[0], C = x.dims[1], H = x.dims[2], W = x.dims[3];
    int64_t OH = (H + pads[0] + pads[2] - ks[0]) / strides[0] + 1;
    int64_t OW = (W + pads[1] + pads[3] - ks[1]) / strides[1] + 1;
    Tensor o;
    o.dtype = DT_F32;
    o.dims = {N, C, OH, OW};
    o.alloc();
    for (int64_t nn = 0; nn < N; ++nn)
      for (int64_t c = 0; c < C; ++c)
        for (int64_t oh = 0; oh < OH; ++oh)
          for (int64_t ow = 0; ow < OW; ++ow) {
            double best = -1e30, sum = 0;
            int64_t cnt = 0;
            for (int64_t kh = 0; kh < ks[0]; ++kh)
              for (int64_t kw = 0; kw < ks[1]; ++kw) {
                int64_t ih = oh * strides[0] - pads[0] + kh;
                int64_t iw = ow * strides[1] - pads[1] + kw;
                if (ih < 0 || ih >= H || iw < 0 || iw >= W) continue;
                double v = x.at(((nn * C + c) * H + ih) * W + iw);
                best = std::max(best, v);
                sum += v;
                ++cnt;
              }
            double denom = include_pad ? double(ks[0] * ks[1])
                                       : double(std::max(cnt, int64_t(1)));
            o.f[size_t(((nn * C + c) * OH + oh) * OW + ow)] =
                float(op == "MaxPool" ? best : sum / denom);
          }
    out(std::move(o));
  } else if (op == "ReduceSum" || op == "ReduceMax" || op == "ReduceMin" ||
             op == "ReduceProd" || op == "ReduceMean") {
    const Tensor& a = in(n, 0);
    std::vector<int64_t> axes = attr_ints(n, "axes");
    if (axes.empty() && n.inputs.size() > 1)
      axes.assign(in(n, 1).i.begin(), in(n, 1).i.end());
    bool keep = attr_i(n, "keepdims", 1) != 0;
    std::vector<bool> red(a.dims.size(), axes.empty());
    for (auto ax : axes)
      red[size_t(ax < 0 ? ax + int64_t(a.dims.size()) : ax)] = true;
    Tensor o;
    o.dtype = a.dtype;
    for (size_t d = 0; d < a.dims.size(); ++d) {
      if (!red[d]) o.dims.push_back(a.dims[d]);
      else if (keep) o.dims.push_back(1);
    }
    o.alloc();
    const int rc = op == "ReduceMax" ? 1 : op == "ReduceMin" ? 2
                   : op == "ReduceProd" ? 3 : op == "ReduceMean" ? 4 : 0;
    const double init = rc == 1 ? -1e300 : rc == 2 ? 1e300
                        : rc == 3 ? 1.0 : 0.0;
    // fast path: reduced axes form a contiguous SUFFIX (softmax/LN
    // reductions after export are all last-axis) — contiguous row
    // scans instead of per-element rank-deep div/mod
    size_t split = a.dims.size();
    while (split > 0 && red[split - 1]) --split;
    bool suffix = true;
    for (size_t d = 0; d < split; ++d)
      if (red[d]) { suffix = false; break; }
    if (suffix && a.is_float()) {
      int64_t inner = 1, outer = 1;
      for (size_t d = split; d < a.dims.size(); ++d) inner *= a.dims[d];
      for (size_t d = 0; d < split; ++d) outer *= a.dims[d];
      const float* af = a.f.data();
      for (int64_t ou = 0; ou < outer; ++ou) {
        const float* row = af + ou * inner;
        double accv = init;
        switch (rc) {
          case 1:
            for (int64_t j = 0; j < inner; ++j)
              accv = std::max(accv, double(row[j]));
            break;
          case 2:
            for (int64_t j = 0; j < inner; ++j)
              accv = std::min(accv, double(row[j]));
            break;
          case 3:
            for (int64_t j = 0; j < inner; ++j) accv *= row[j];
            break;
          default:
            for (int64_t j = 0; j < inner; ++j) accv += row[j];
        }
        if (rc == 4) accv /= double(inner);
        o.f[size_t(ou)] = float(accv);
      }
      out(std::move(o));
      return;
    }
    std::vector<double> acc(size_t(o.numel()), init);
    std::vector<int64_t> counts(size_t(o.numel()), 0);
    auto istr = strides_for(a.dims);
    auto ostr = strides_for(o.dims);
    for (int64_t k = 0; k < a.numel(); ++k) {
      int64_t dst = 0;
      size_t od = 0;
      for (size_t d = 0; d < a.dims.size(); ++d) {
        int64_t coord = (k / istr[d]) % a.dims[d];
        if (!red[d]) dst += coord * ostr[od++];
        else if (keep) od++;  // coord 0
      }
      double v = a.at(k);
      switch (rc) {
        case 1: acc[size_t(dst)] = std::max(acc[size_t(dst)], v); break;
        case 2: acc[size_t(dst)] = std::min(acc[size_t(dst)], v); break;
        case 3: acc[size_t(dst)] *= v; break;
        default: acc[size_t(dst)] += v;
      }
      counts[size_t(dst)]++;
    }
    for (int64_t k = 0; k < o.numel(); ++k)
      o.set(k, rc == 4 ? acc[size_t(k)] / double(counts[size_t(k)])
                       : acc[size_t(k)]);
    out(std::move(o));
  } else if (op == "ArgMax" || op == "ArgMin") {
    const Tensor& a = in(n, 0);
    int64_t axis = attr_i(n, "axis", 0);
    if (axis < 0) axis += int64_t(a.dims.size());
    bool keep = attr_i(n, "keepdims", 1) != 0;
    Tensor o;
    o.dtype = DT_I64;
    for (size_t d = 0; d < a.dims.size(); ++d) {
      if (int64_t(d) != axis) o.dims.push_back(a.dims[d]);
      else if (keep) o.dims.push_back(1);
    }
    o.alloc();
    auto istr = strides_for(a.dims);
    int64_t ax_dim = a.dims[size_t(axis)];
    for (int64_t k = 0; k < o.numel(); ++k) {
      // decompose k into non-axis coords
      int64_t base = 0;
      size_t od = 0;
      auto ostr = strides_for(o.dims);
      for (size_t d = 0; d < a.dims.size(); ++d) {
        if (int64_t(d) == axis) { if (keep) od++; continue; }
        base += ((k / ostr[od]) % o.dims[od]) * istr[d];
        od++;
      }
      double best = op == "ArgMax" ? -1e300 : 1e300;
      int64_t arg = 0;
      for (int64_t j = 0; j < ax_dim; ++j) {
        double v = a.at(base + j * istr[size_t(axis)]);
        if ((op == "ArgMax" && v > best) || (op == "ArgMin" && v < best)) {
          best = v;
          arg = j;
        }
      }
      o.i[size_t(k)] = arg;
    }
    out(std::move(o));
  } else if (op == "CumSum") {
    const Tensor& a = in(n, 0);
    int64_t axis = int64_t(in(n, 1).at(0));
    if (axis < 0) axis += int64_t(a.dims.size());
    Tensor o = a;
    auto istr = strides_for(a.dims);
    int64_t ax_dim = a.dims[size_t(axis)];
    for (int64_t k = 0; k < a.numel(); ++k) {
      int64_t coord = (k / istr[size_t(axis)]) % ax_dim;
      if (coord > 0) o.set(k, o.at(k) + o.at(k - istr[size_t(axis)]));
    }
    out(std::move(o));
  } else if (op == "Pad") {
    const Tensor& a = in(n, 0);
    const Tensor& pads = in(n, 1);
    double cval = n.inputs.size() > 2 ? in(n, 2).at(0) : 0.0;
    size_t rank = a.dims.size();
    Tensor o;
    o.dtype = a.dtype;
    for (size_t d = 0; d < rank; ++d)
      o.dims.push_back(a.dims[d] + pads.i[d] + pads.i[d + rank]);
    o.alloc();
    for (int64_t k = 0; k < o.numel(); ++k) o.set(k, cval);
    auto istr = strides_for(a.dims);
    auto ostr = strides_for(o.dims);
    for (int64_t k = 0; k < a.numel(); ++k) {
      int64_t dst = 0;
      for (size_t d = 0; d < rank; ++d)
        dst += (((k / istr[d]) % a.dims[d]) + pads.i[d]) * ostr[d];
      o.set(dst, a.at(k));
    }
    out(std::move(o));
  } else if (op == "Softmax") {
    const Tensor& a = in(n, 0);
    int64_t axis = attr_i(n, "axis", -1);
    if (axis < 0) axis += int64_t(a.dims.size());
    Tensor o = a;
    auto istr = strides_for(a.dims);
    int64_t ax_dim = a.dims[size_t(axis)];
    int64_t outer = a.numel() / ax_dim;
    for (int64_t b = 0; b < outer; ++b) {
      // map outer index to base offset
      int64_t base = 0, rem = b;
      for (size_t d = 0; d < a.dims.size(); ++d) {
        if (int64_t(d) == axis) continue;
        int64_t sz = a.dims[d];
        // recompute strides over non-axis dims (row-major)
        int64_t block = 1;
        for (size_t d2 = d + 1; d2 < a.dims.size(); ++d2)
          if (int64_t(d2) != axis) block *= a.dims[d2];
        int64_t coord = (rem / block) % sz;
        base += coord * istr[d];
      }
      double mx = -1e300;
      for (int64_t j = 0; j < ax_dim; ++j)
        mx = std::max(mx, a.at(base + j * istr[size_t(axis)]));
      double sum = 0;
      for (int64_t j = 0; j < ax_dim; ++j)
        sum += std::exp(a.at(base + j * istr[size_t(axis)]) - mx);
      for (int64_t j = 0; j < ax_dim; ++j) {
        int64_t at = base + j * istr[size_t(axis)];
        o.set(at, std::exp(a.at(at) - mx) / sum);
      }
    }
    out(std::move(o));
  } else {
    throw std::runtime_error("op '" + op + "' not supported by the native "
                             "predictor (re-export or extend "
                             "csrc/ptpu_predictor.cc)");
  }
}

void fill_error(char* err, int err_len, const std::string& msg) {
  if (err && err_len > 0) {
    std::snprintf(err, size_t(err_len), "%s", msg.c_str());
  }
}

}  // namespace

// -------------------------------------------------------------------- C ABI
/* Integer inputs (token ids, lengths) — the reference C API exposes
 * PD_DataType INT32/INT64 (`capi_exp/pd_inference_api.h`); without
 * these, embedding/transformer artifacts cannot be served natively. */
/* Caller-supplied dims are untrusted: a negative ndim/dim or an
 * int64-overflowing product would produce a bogus numel() and an
 * out-of-bounds read of `data`. ndim == 0 is a valid scalar (empty
 * dims, numel 1); dims may then be null. */
static void check_dims(const int64_t* dims, int ndim) {
  if (ndim < 0) throw std::runtime_error("set_input: ndim must be >= 0");
  if (ndim > 0 && !dims)
    throw std::runtime_error("set_input: dims is null");
  int64_t n = 1;
  for (int k = 0; k < ndim; ++k) {
    if (dims[k] < 0)
      throw std::runtime_error("set_input: negative dim at index " +
                               std::to_string(k));
    if (dims[k] > 0 && n > (int64_t(1) << 40) / dims[k])
      throw std::runtime_error("set_input: element count overflows "
                               "the 2^40 sanity cap");
    n *= dims[k];
  }
}

template <class T>
static int set_input_int(void* h, const char* name, const T* data,
                         const int64_t* dims, int ndim, int dtype,
                         char* err, int err_len) {
  try {
    check_dims(dims, ndim);
    auto* p = (Predictor*)h;
    Tensor t;
    t.dtype = dtype;
    t.dims.assign(dims, dims + ndim);
    t.i.assign(data, data + t.numel());
    p->env[name] = std::move(t);
    return 0;
  } catch (const std::exception& e) {
    fill_error(err, err_len, e.what());
    return 1;
  }
}

extern "C" {

typedef struct PTPU_Predictor PTPU_Predictor;

__attribute__((visibility("default")))
PTPU_Predictor* ptpu_predictor_create(const char* model_path, char* err,
                                      int err_len) {
  try {
    std::ifstream f(model_path, std::ios::binary);
    if (!f) throw std::runtime_error(std::string("cannot open ") +
                                     model_path);
    std::stringstream ss;
    ss << f.rdbuf();
    auto* p = new Predictor();
    p->g = parse_model(ss.str());
    for (const auto& kv : p->g.initializers) p->env[kv.first] = kv.second;
    p->fold_constants();
    return (PTPU_Predictor*)p;
  } catch (const std::exception& e) {
    fill_error(err, err_len, e.what());
    return nullptr;
  }
}

__attribute__((visibility("default")))
void ptpu_predictor_destroy(PTPU_Predictor* h) {
  delete (Predictor*)h;
}

__attribute__((visibility("default")))
int ptpu_predictor_num_inputs(PTPU_Predictor* h) {
  return int(((Predictor*)h)->g.input_names.size());
}

__attribute__((visibility("default")))
int ptpu_predictor_num_outputs(PTPU_Predictor* h) {
  return int(((Predictor*)h)->g.output_names.size());
}

__attribute__((visibility("default")))
const char* ptpu_predictor_input_name(PTPU_Predictor* h, int i) {
  auto* p = (Predictor*)h;
  if (i < 0 || size_t(i) >= p->g.input_names.size()) return "";
  return p->g.input_names[size_t(i)].c_str();
}

__attribute__((visibility("default")))
int ptpu_predictor_set_input(PTPU_Predictor* h, const char* name,
                             const float* data, const int64_t* dims,
                             int ndim, char* err, int err_len) {
  try {
    check_dims(dims, ndim);
    auto* p = (Predictor*)h;
    Tensor t;
    t.dtype = DT_F32;
    t.dims.assign(dims, dims + ndim);
    t.f.assign(data, data + t.numel());
    p->env[name] = std::move(t);
    return 0;
  } catch (const std::exception& e) {
    fill_error(err, err_len, e.what());
    return 1;
  }
}

__attribute__((visibility("default")))
int ptpu_predictor_set_input_i32(PTPU_Predictor* h, const char* name,
                                 const int32_t* data, const int64_t* dims,
                                 int ndim, char* err, int err_len) {
  return set_input_int(h, name, data, dims, ndim, DT_I32, err, err_len);
}

__attribute__((visibility("default")))
int ptpu_predictor_set_input_i64(PTPU_Predictor* h, const char* name,
                                 const int64_t* data, const int64_t* dims,
                                 int ndim, char* err, int err_len) {
  return set_input_int(h, name, data, dims, ndim, DT_I64, err, err_len);
}

__attribute__((visibility("default")))
int ptpu_predictor_run(PTPU_Predictor* h, char* err, int err_len) {
  try {
    ((Predictor*)h)->run();
    return 0;
  } catch (const std::exception& e) {
    fill_error(err, err_len, e.what());
    return 1;
  }
}

__attribute__((visibility("default")))
int ptpu_predictor_output_ndim(PTPU_Predictor* h, int i) {
  auto* p = (Predictor*)h;
  if (i < 0 || size_t(i) >= p->outputs.size()) return -1;
  return int(p->outputs[size_t(i)].dims.size());
}

__attribute__((visibility("default")))
const int64_t* ptpu_predictor_output_dims(PTPU_Predictor* h, int i) {
  auto* p = (Predictor*)h;
  if (i < 0 || size_t(i) >= p->outputs.size()) return nullptr;
  return p->outputs[size_t(i)].dims.data();
}

// Output data as float32 (int outputs are converted in place once).
__attribute__((visibility("default")))
const float* ptpu_predictor_output_data(PTPU_Predictor* h, int i) {
  auto* p = (Predictor*)h;
  if (i < 0 || size_t(i) >= p->outputs.size()) return nullptr;
  Tensor& t = p->outputs[size_t(i)];
  if (!t.is_float() && t.f.size() != size_t(t.numel())) {
    t.f.resize(size_t(t.numel()));
    for (int64_t k = 0; k < t.numel(); ++k) t.f[size_t(k)] = float(t.i[k]);
  }
  return t.f.data();
}

}  // extern "C"
